"""Tuner: trial controller over the actor API.

Reference analogs: ``python/ray/tune/tuner.py`` (Tuner.fit),
``tune/execution/tune_controller.py`` (event loop managing trial actors),
``tune/result_grid.py``. Trials reuse the Train layer's worker actor
(``TrainWorker``) — the reference made the same unification (tune trials
report via ``ray.train.report``).
"""
from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.backoff import Backoff
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Searcher,
)

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class TuneConfig:
    """(reference: ``tune/tune_config.py``)"""

    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    trial_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    seed: Optional[int] = None


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any], trial_dir: str):
        self.trial_id = trial_id
        self.config = config
        self.trial_dir = trial_dir
        self.status = PENDING
        self.actor = None
        self.metrics_history: List[dict] = []
        self.last_result: Dict[str, Any] = {}
        self.latest_checkpoint: Optional[str] = None
        self.error: Optional[str] = None
        self.iteration = 0

    def result(self) -> Result:
        return Result(
            metrics=self.last_result,
            config=dict(self.config),
            checkpoint=(
                Checkpoint(self.latest_checkpoint)
                if self.latest_checkpoint else None
            ),
            path=self.trial_dir,
            error=self.error,
            metrics_history=self.metrics_history,
        )


class ResultGrid:
    """(reference: ``tune/result_grid.py``)"""

    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        return self._trials[i].result()

    @property
    def num_errors(self) -> int:
        return sum(1 for t in self._trials if t.status == ERROR)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        best, best_v = None, None
        for t in self._trials:
            v = t.last_result.get(metric)
            if v is None:
                continue
            if best_v is None or (v < best_v if mode == "min" else v > best_v):
                best, best_v = t, v
        if best is None:
            raise RuntimeError("no trial reported the target metric")
        return best.result()

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = dict(t.last_result)
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            for k, v in t.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore: Optional[dict] = None  # set by Tuner.restore

    @classmethod
    def restore(cls, path: str, trainable: Callable, *,
                resume_errored: bool = False,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its run directory
        (reference: ``Tuner.restore`` + ``tune/execution/experiment_state.py``).
        Finished trials keep their results; unfinished (and, with
        ``resume_errored``, failed) trials restart from their latest
        checkpoint; the searcher continues where it stopped when its
        pickled state is readable."""
        state = _load_experiment_state(path)
        if state is None:
            raise FileNotFoundError(
                f"no experiment state under {path!r} "
                f"(expected {_STATE_FILE})"
            )
        run_dir = os.path.abspath(path)
        if tune_config is None and state.get("tune_config"):
            # carry the original experiment's metric/mode/limits forward
            tune_config = TuneConfig(**state["tune_config"])
        tuner = cls(
            trainable,
            tune_config=tune_config,
            run_config=RunConfig(
                name=os.path.basename(run_dir),
                storage_path=os.path.dirname(run_dir),
            ),
        )
        tuner._restore = {
            "state": state,
            "resume_errored": resume_errored,
            "run_dir": run_dir,
        }
        return tuner

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        name = self._run_config.name or f"tune_{int(time.time())}"
        run_dir = os.path.join(self._run_config.resolved_storage_path(), name)
        os.makedirs(run_dir, exist_ok=True)

        searcher = tc.search_alg or BasicVariantGenerator(
            self._param_space, num_samples=tc.num_samples, seed=tc.seed
        )
        scheduler = tc.scheduler or FIFOScheduler()
        initial_trials: List[Trial] = []
        counter = 0
        if self._restore is not None:
            state = self._restore["state"]
            run_dir = self._restore["run_dir"]
            counter = state.get("counter", 0)
            search_state = _load_search_state(run_dir)
            if search_state.get("searcher") is not None:
                searcher = search_state["searcher"]
            elif tc.search_alg is None:
                # No searcher state to continue from and none supplied:
                # resume only the recorded trials, don't invent new ones.
                searcher = _ExhaustedSearcher()
            if tc.scheduler is None:
                if search_state.get("scheduler") is not None:
                    scheduler = search_state["scheduler"]
                elif not isinstance(scheduler, FIFOScheduler):
                    pass  # user supplied one via tune_config
                else:
                    import logging

                    logging.getLogger(__name__).warning(
                        "Tuner.restore: original scheduler state "
                        "unavailable; resuming under FIFOScheduler "
                        "(pass tune_config=TuneConfig(scheduler=...) to "
                        "restore early stopping/PBT behavior)"
                    )
            initial_trials = _trials_from_state(
                state, run_dir, self._restore["resume_errored"]
            )
        controller = _TrialRunner(
            self._trainable, searcher, scheduler, tc, run_dir,
            initial_trials=initial_trials, counter=counter,
        )
        trials = controller.run()
        return ResultGrid(trials, tc.metric, tc.mode)


_STATE_FILE = "experiment_state.json"
_SEARCHER_FILE = "searcher.pkl"


class _ExhaustedSearcher(Searcher):
    """Yields nothing: used on restore when the original searcher's state
    is unavailable (resuming recorded trials must not mint new ones)."""

    def suggest(self, trial_id: str):
        return None


def _load_experiment_state(run_dir: str) -> Optional[dict]:
    import json

    try:
        with open(os.path.join(run_dir, _STATE_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_search_state(run_dir: str) -> dict:
    """{"searcher": ..., "scheduler": ...} — empty on any failure
    (unpicklable state: unfinished trials still resume)."""
    try:
        import cloudpickle

        with open(os.path.join(run_dir, _SEARCHER_FILE), "rb") as f:
            state = cloudpickle.load(f)
        return state if isinstance(state, dict) else {"searcher": state}
    except Exception:
        return {}


def _trials_from_state(state: dict, run_dir: str,
                       resume_errored: bool) -> List[Trial]:
    trials: List[Trial] = []
    for rec in state.get("trials", []):
        t = Trial(
            rec["trial_id"], rec["config"],
            os.path.join(run_dir, rec["trial_id"]),
        )
        t.status = rec["status"]
        t.iteration = rec.get("iteration", 0)
        t.last_result = rec.get("last_result", {})
        t.metrics_history = rec.get("metrics_history", [])
        t.latest_checkpoint = rec.get("latest_checkpoint")
        t.error = rec.get("error")
        if t.status == RUNNING or (resume_errored and t.status == ERROR):
            # re-run from the latest checkpoint
            t.status = PENDING
            t.error = None
        trials.append(t)
    return trials


class _TrialRunner:
    """The trial event loop (reference: ``execution/tune_controller.py``),
    checkpointing experiment state so interrupted runs resume."""

    def __init__(self, trainable, searcher, scheduler, tc: TuneConfig,
                 run_dir: str, initial_trials: Optional[List[Trial]] = None,
                 counter: int = 0):
        self._trainable = trainable
        self._searcher = searcher
        self._scheduler = scheduler
        self._tc = tc
        self._run_dir = run_dir
        self._trials: List[Trial] = list(initial_trials or [])
        self._counter = counter
        self._fits = 1
        self._fits_at = -10.0
        self._state_saved_at = -10.0

    # ------------------------------------------------------- experiment state

    def _save_state(self, force: bool = False):
        """Periodic experiment snapshot (reference:
        ``execution/experiment_state.py``): trial table + searcher state,
        written atomically so a crash mid-write cannot corrupt resume."""
        import json
        import tempfile

        now = time.monotonic()
        if not force and now - self._state_saved_at < 1.0:
            return
        self._state_saved_at = now
        state = {
            "version": 1,
            "counter": self._counter,
            "tune_config": {
                "metric": self._tc.metric,
                "mode": self._tc.mode,
                "num_samples": self._tc.num_samples,
                "max_concurrent_trials": self._tc.max_concurrent_trials,
                "trial_resources": self._tc.trial_resources,
            },
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "status": t.status,
                    "iteration": t.iteration,
                    "last_result": t.last_result,
                    "metrics_history": t.metrics_history[-100:],
                    "latest_checkpoint": t.latest_checkpoint,
                    "error": t.error,
                }
                for t in self._trials
            ],
        }
        try:
            fd, tmp = tempfile.mkstemp(dir=self._run_dir, prefix=".state_")
            with os.fdopen(fd, "w") as f:
                json.dump(state, f, default=str)
            os.replace(tmp, os.path.join(self._run_dir, _STATE_FILE))
        except OSError:
            pass
        try:
            import cloudpickle

            blob = cloudpickle.dumps(
                {"searcher": self._searcher, "scheduler": self._scheduler}
            )
            fd, tmp = tempfile.mkstemp(dir=self._run_dir, prefix=".searcher_")
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self._run_dir, _SEARCHER_FILE))
        except Exception:
            pass  # unpicklable searcher/scheduler (e.g. live study handles)

    # ------------------------------------------------------------ lifecycle

    def _next_trial(self):
        """Trial, "PENDING" (retry later), or None (search exhausted)."""
        # restored-but-unfinished trials launch before new suggestions
        for t in self._trials:
            if t.status == PENDING and t.actor is None:
                return t
        tid = f"trial_{self._counter:05d}"
        cfg = self._searcher.suggest(tid)
        if cfg is None or cfg == "PENDING":
            return cfg
        self._counter += 1
        t = Trial(tid, cfg, os.path.join(self._run_dir, tid))
        self._trials.append(t)
        return t

    def _max_concurrent(self) -> int:
        cap = self._tc.max_concurrent_trials or 2 ** 30
        # cluster_resources is a full node scan — cache it; total capacity
        # only changes on node add/remove, not per 20ms controller tick
        now = time.monotonic()
        if now - self._fits_at > 1.0:
            try:
                import ray_tpu

                avail = ray_tpu.cluster_resources()
                per = self._tc.trial_resources
                self._fits = int(min(
                    (avail.get(k, 0.0) // v) for k, v in per.items() if v > 0
                ))
            except Exception:
                self._fits = 4  # no cluster metadata: modest default
            self._fits_at = now
        return max(1, min(cap, self._fits))

    def _start_trial(self, trial: Trial,
                     checkpoint_path: Optional[str] = None):
        import ray_tpu
        from ray_tpu.train.worker_group import TrainWorker

        res = self._tc.trial_resources
        actor_cls = ray_tpu.remote(TrainWorker)
        opts = {
            "num_cpus": res.get("CPU", 1.0),
            "resources": {k: v for k, v in res.items() if k != "CPU"},
        }
        trial.actor = actor_cls.options(**opts).remote()
        ckpt = checkpoint_path or trial.latest_checkpoint
        ray_tpu.get(
            trial.actor.setup.remote(
                0, 1, 0, 1, 0, trial.trial_id, trial.trial_dir, ckpt, {},
                None, trial.iteration,
            ),
            timeout=60,
        )
        ray_tpu.get(
            trial.actor.start.remote(self._trainable, trial.config), timeout=60
        )
        trial.status = RUNNING
        self._scheduler.on_trial_start(trial)

    def _stop_trial(self, trial: Trial, status: str = TERMINATED):
        import ray_tpu

        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        trial.status = status
        self._searcher.on_trial_complete(
            trial.trial_id, trial.last_result, error=(status == ERROR)
        )

    # ------------------------------------------------------------ main loop

    def run(self) -> List[Trial]:
        import ray_tpu

        exhausted = False
        poll = Backoff(base=0.02, cap=0.25)
        while True:
            running = [t for t in self._trials if t.status == RUNNING]
            # launch up to the concurrency/resource cap
            while not exhausted and len(running) < self._max_concurrent():
                t = self._next_trial()
                if t is None:
                    exhausted = True
                    break
                if t == "PENDING":
                    break  # concurrency-limited: retry next loop
                try:
                    self._start_trial(t)
                    running.append(t)
                except Exception as e:
                    t.error = f"start failed: {e}"
                    self._stop_trial(t, ERROR)
            if not running:
                if exhausted and all(
                    t.status in (TERMINATED, ERROR) for t in self._trials
                ):
                    self._save_state(force=True)
                    return self._trials
                poll.sleep()
                continue
            poll.reset()
            for trial in running:
                self._poll_trial(trial)
            self._save_state()
            poll.sleep()

    def _poll_trial(self, trial: Trial):
        import ray_tpu

        try:
            h = ray_tpu.get(trial.actor.poll.remote(), timeout=30)
        except Exception as e:
            trial.error = f"trial actor unreachable: {e}"
            self._stop_trial(trial, ERROR)
            return
        decision = CONTINUE
        for rep in h["reports"]:
            trial.iteration += 1
            result = dict(rep["metrics"])
            result.setdefault("training_iteration", trial.iteration)
            trial.last_result = result
            trial.metrics_history.append(result)
            if rep.get("checkpoint_path"):
                trial.latest_checkpoint = rep["checkpoint_path"]
            d = self._scheduler.on_result(trial, result)
            if d == STOP:
                decision = STOP
                break  # discard reports past the stop decision
        if decision == STOP:
            self._stop_trial(trial, TERMINATED)
            return
        # PBT exploit/explore at perturbation boundaries
        exploit = self._scheduler.choose_exploit(trial, self._trials)
        if exploit is not None:
            source, new_config = exploit
            if source.latest_checkpoint:
                self._stop_trial(trial, TERMINATED)
                clone = Trial(
                    f"{trial.trial_id}_pbt{trial.iteration}",
                    new_config,
                    os.path.join(self._run_dir,
                                 f"{trial.trial_id}_pbt{trial.iteration}"),
                )
                clone.iteration = source.iteration
                clone.metrics_history = list(trial.metrics_history)
                self._trials.append(clone)
                try:
                    self._start_trial(
                        clone, checkpoint_path=source.latest_checkpoint
                    )
                except Exception as e:
                    clone.error = f"pbt restart failed: {e}"
                    self._stop_trial(clone, ERROR)
                return
        if h["error"]:
            trial.error = h["error"]
            self._stop_trial(trial, ERROR)
        elif h["done"]:
            self._stop_trial(trial, TERMINATED)
