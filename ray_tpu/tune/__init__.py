"""ray_tpu.tune: hyperparameter tuning over trial actors.

Reference analog: ``python/ray/tune``. Trials report via the same
``report``/``get_checkpoint`` used in train_fns (the reference unified these
too)::

    from ray_tpu import tune

    def objective(config):
        for step in range(10):
            tune.report({"loss": (config["lr"] - 0.1) ** 2 + 1 / (step + 1)})

    grid = tune.Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-4, 1e-1)},
        tune_config=tune.TuneConfig(metric="loss", mode="min", num_samples=8,
                                    scheduler=tune.ASHAScheduler()),
    ).fit()
    best = grid.get_best_result()
"""
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.context import get_checkpoint, get_context, report
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BayesOptSearch,
    HyperOptSearch,
    NevergradSearch,
    OptunaSearch,
    BasicVariantGenerator,
    Choice,
    ConcurrencyLimiter,
    Domain,
    Searcher,
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.tuner import ResultGrid, Trial, TuneConfig, Tuner

__all__ = [
    "ASHAScheduler",
    "BasicVariantGenerator",
    "Checkpoint",
    "Choice",
    "ConcurrencyLimiter",
    "Domain",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "Trial",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_context",
    "grid_search",
    "BayesOptSearch",
    "HyperOptSearch",
    "NevergradSearch",
    "OptunaSearch",
    "lograndint",
    "loguniform",
    "quniform",
    "randint",
    "randn",
    "report",
    "sample_from",
    "uniform",
]
