"""Vocabulary-chunked softmax cross-entropy.

The naive loss materializes float32 logits of shape [B, T, V] — for
GPT-2-small at B=16, T=1024 that is a 3.3 GB tensor written and re-read
several times by softmax and its backward, all pure HBM traffic on the
step's critical path. Here the head projection + logsumexp + gold-logit
gather run per sequence chunk inside a remat'd scan body: peak residency is
one [B, c, V] chunk and the backward recomputes each chunk's logits instead
of loading them.

Reference context: the reference ships no model/loss code (SURVEY §5 —
models are user code / delegated to vLLM); this is part of our TPU-native
training stack, same role as the fused-CE kernels in public LLM trainers.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def chunked_softmax_xent(
    x: jax.Array,
    head_w: jax.Array,
    targets: jax.Array,
    mask: Optional[jax.Array] = None,
    chunk: int = 512,
) -> jax.Array:
    """Mean next-token NLL without a [B, T, V] intermediate.

    x: [B, T, E] final-trunk features (pre-head). head_w: [V, E] (the tied
    embedding or LM head). targets: [B, T] int ids. mask: optional [B, T]
    weights (0 drops a position).
    """
    B, T, E = x.shape
    c = min(chunk, T)
    pad = (-T) % c  # pad the tail chunk instead of shrinking the chunk
    # (a divisor search would degenerate to c=1 for prime T — a T-step
    # sequential scan of tiny matmuls)
    if mask is None:
        mask = jnp.ones((B, T), jnp.float32)
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))  # pad rows weigh zero
        T += pad
    n = T // c
    xc = x.reshape(B, n, c, E).transpose(1, 0, 2, 3)   # [n, B, c, E]
    tc = targets.reshape(B, n, c).transpose(1, 0, 2)   # [n, B, c]
    mc = mask.reshape(B, n, c).transpose(1, 0, 2).astype(jnp.float32)

    def body(carry, xs):
        s, cnt = carry
        xcb, tcb, mcb = xs
        # Keep the [B, c, V] tensor in the activation dtype: a float32 copy
        # here doubles the chunk's HBM traffic AND gets materialized (it
        # would have two consumers). The reductions below cast f32 inside
        # their fusions instead.
        logits = jnp.einsum("bce,ve->bcv", xcb, head_w.astype(xcb.dtype))
        m = jnp.max(logits, axis=-1).astype(jnp.float32)          # [B, c]
        expsum = jnp.sum(
            jnp.exp((logits.astype(jnp.float32) - m[..., None])), axis=-1
        )
        lse = m + jnp.log(expsum)
        # Gold logit gathered from the SAME tensor the logsumexp reduced:
        # numerator and denominator share one precision, so lse >= gold
        # always and per-token NLL cannot go negative. (An f32 recompute of
        # the gold row dot is more precise in isolation but inconsistent
        # with the bf16 lse — and costs a [B, c, E] f32 gather + einsum.)
        gold = jnp.take_along_axis(
            logits, tcb[..., None], axis=-1
        )[..., 0].astype(jnp.float32)
        s = s + ((lse - gold) * mcb).sum()
        cnt = cnt + mcb.sum()
        return (s, cnt), None

    # Remat per chunk: the backward re-projects the chunk's logits rather
    # than keeping them alive across the whole scan.
    body = jax.checkpoint(body)
    (s, cnt), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (xc, tc, mc)
    )
    return s / jnp.maximum(cnt, 1.0)
