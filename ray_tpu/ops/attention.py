"""Attention ops: XLA reference impl + pallas TPU flash-attention kernel.

The reference framework has no attention code of its own (it delegates to
vLLM/torch — SURVEY.md §2.3/§5); in a TPU-native stack the kernel layer is
ours. Design:

- ``attention_xla``: einsum softmax attention. XLA fuses this well on TPU and
  it is the autodiff path.
- ``flash_attention``: blockwise online-softmax pallas kernel (VMEM-resident
  q/k/v blocks, f32 accumulators, causal short-circuit per block row).
  Forward = pallas; backward = recompute via the XLA path (custom_vjp), so
  training gets flash's forward memory profile with correct grads.
- ``attention``: dispatcher — pallas on TPU, interpret-mode pallas or XLA
  elsewhere (tests run the same kernel code on the CPU mesh).

Shapes follow [batch, seq, heads, head_dim] throughout.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    bias: Optional[jax.Array] = None,
    q_offset: int | jax.Array = 0,
    kv_offset: int | jax.Array = 0,
) -> jax.Array:
    """Dense attention. q: [B, Tq, H, D]; k/v: [B, Tk, Hkv, D].

    Supports grouped-query attention (H a multiple of Hkv) and absolute
    position offsets so callers holding only a chunk of the sequence (ring /
    blockwise) mask correctly.
    """
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = D ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0) + q_offset
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1) + kv_offset
        mask = q_pos >= k_pos
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------------- pallas

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, seq_k: int):
    """One (batch*head, q_block) program: stream K/V blocks with online
    softmax. Block shapes: q/o [1, Bq, D], k/v [1, Tk, D]."""
    q_idx = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale  # [Bq, D]

    num_k_blocks = pl.cdiv(seq_k, block_k)
    if causal:
        # Highest K block this Q block row can see (short-circuits the rest).
        last_block = jax.lax.div((q_idx + 1) * block_q - 1, block_k) + 1
        num_iter = jnp.minimum(num_k_blocks, last_block)
    else:
        num_iter = num_k_blocks

    def body(i, carry):
        o_acc, m, l = carry
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jnp.dot(q, k_blk.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)  # [Bq, Bk]
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        # Inputs are padded to block multiples; mask keys past the true
        # sequence end so the pad rows never contribute.
        mask = k_pos < seq_k
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * alpha + jnp.dot(
            p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o_acc, m, l = jax.lax.fori_loop(0, num_iter, body, (o0, m0, l0))
    o_ref[0] = (o_acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, *, causal: bool, block_q: int, block_k: int,
                    interpret: bool) -> jax.Array:
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    scale = D ** -0.5
    # Pad sequences to block multiples: in-kernel dynamic slices on a
    # non-multiple tail would clamp and silently re-read earlier rows.
    # Pad keys are masked in-kernel via seq_k; pad q rows are sliced off.
    Tq_p = block_q * ((Tq + block_q - 1) // block_q)
    Tk_p = block_k * ((Tk + block_k - 1) // block_k)
    if Tq_p != Tq:
        q = jnp.pad(q, ((0, 0), (0, Tq_p - Tq), (0, 0), (0, 0)))
    if Tk_p != Tk:
        k = jnp.pad(k, ((0, 0), (0, Tk_p - Tk), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Tk_p - Tk), (0, 0), (0, 0)))
    # Fold batch and heads into the grid's leading dim.
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq_p, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk_p, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk_p, D)
    grid = (B * H, Tq_p // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, causal=causal, scale=scale, seq_k=Tk
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk_p, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(B, H, Tq_p, D).transpose(0, 2, 1, 3)
    return out[:, :Tq] if Tq_p != Tq else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """Flash attention: pallas forward, recompute-XLA backward."""
    return _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention_xla(q, k, v, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


_PALLAS_OK = None


def pallas_available() -> bool:
    """Whether pallas kernels can actually lower on this backend. A backend
    may report "tpu" yet lack mosaic lowering (e.g. remote-tunnel device
    plugins); "auto" must then fall back to XLA attention rather than fail
    at compile time. Probed once with a tiny kernel."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            q = jnp.zeros((1, 128, 1, 128), jnp.float32)
            jax.jit(
                lambda q: flash_attention(q, q, q, True, 128, 128, False)
            )(q).block_until_ready()
            _PALLAS_OK = True
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "pallas unavailable on this backend (%s: %s); "
                "attention_impl='auto' falls back to XLA for this process",
                type(e).__name__, e,
            )
            _PALLAS_OK = False
    return _PALLAS_OK


def attention(
    q, k, v, *, causal: bool = True, impl: str = "auto",
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
):
    """Dispatcher. impl: auto | xla | flash | flash_interpret."""
    if impl == "auto":
        impl = (
            "flash"
            if jax.default_backend() == "tpu" and pallas_available()
            else "xla"
        )
    if impl == "xla":
        return attention_xla(q, k, v, causal=causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal, block_q, block_k, False)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal, block_q, block_k, True)
    raise ValueError(f"unknown attention impl {impl}")
