"""Attention ops: XLA reference impl + pallas TPU flash-attention kernel.

The reference framework has no attention code of its own (it delegates to
vLLM/torch — SURVEY.md §2.3/§5); in a TPU-native stack the kernel layer is
ours. Design:

- ``attention_xla``: einsum softmax attention. XLA fuses this well on TPU and
  it is the autodiff path.
- ``flash_attention``: blockwise online-softmax pallas kernel (VMEM-resident
  q/k/v blocks, f32 accumulators, causal short-circuit per block row).
  Forward AND backward are pallas (FlashAttention-2-style tiling): the
  forward saves per-row logsumexp; the backward streams K/V (dq) and Q/dO
  (dk/dv) blocks and never materializes the [Tq, Tk] score matrix.
- ``attention``: dispatcher — pallas on TPU, interpret-mode pallas or XLA
  elsewhere (tests run the same kernel code on the CPU mesh).

Shapes follow [batch, seq, heads, head_dim] throughout.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    bias: Optional[jax.Array] = None,
    q_offset: int | jax.Array = 0,
    kv_offset: int | jax.Array = 0,
) -> jax.Array:
    """Dense attention. q: [B, Tq, H, D]; k/v: [B, Tk, Hkv, D].

    Supports grouped-query attention (H a multiple of Hkv) and absolute
    position offsets so callers holding only a chunk of the sequence (ring /
    blockwise) mask correctly.
    """
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = D ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0) + q_offset
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1) + kv_offset
        mask = q_pos >= k_pos
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------------- pallas

DEFAULT_BLOCK_Q = 512  # swept on v5e (B=32, T=1024, D=64): 512/512 runs the
DEFAULT_BLOCK_K = 512  # fwd 23% and fwd+bwd 23% faster than 256/256


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int,
                  causal: bool, scale: float, seq_k: int):
    """One (batch*head, q_block) program: stream K/V blocks with online
    softmax. Block shapes: q/o [1, Bq, D], k/v [1, Tk, D], lse [1, 8, Bq].
    The logsumexp row statistics (written only when the training path asks
    for them) feed the pallas backward."""
    q_idx = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    # Operands stay in the input dtype (bf16): the MXU runs low-precision
    # multiplies with f32 accumulation (preferred_element_type) at ~2x the
    # f32xf32 rate — casting up front would halve kernel throughput. The
    # scale is applied to the f32 scores, not the bf16 q (no rounding).
    q = q_ref[0]  # [Bq, D]

    num_k_blocks = pl.cdiv(seq_k, block_k)
    if causal:
        # Highest K block this Q block row can see (short-circuits the rest).
        last_block = ((q_idx + 1) * block_q - 1) // block_k + 1
        num_iter = jnp.minimum(num_k_blocks, last_block)
    else:
        num_iter = num_k_blocks

    def body(i, carry):
        o_acc, m, l = carry
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        # Inputs are padded to block multiples; mask keys past the true
        # sequence end so the pad rows never contribute.
        mask = k_pos < seq_k
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p in [0, 1]: bf16 rounding is harmless and keeps PV on the fast
        # MXU path (f32 accumulator preserves the sum's precision).
        o_new = o_acc * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o_acc, m, l = jax.lax.fori_loop(0, num_iter, body, (o0, m0, l0))
    o_ref[0] = (o_acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if lse_ref is not None:
        # lse = m + log(l). Stored 8x-replicated on the sublane dim: mosaic
        # requires block shapes (8, 128)-divisible, so a [Bq]-vector per
        # program rides as an [8, Bq] tile (negligible bytes, legal layout).
        lse = jnp.maximum(m, NEG_INF) + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse[:, 0][None, :], (8, block_q))


def _flash_fwd_impl(q, k, v, *, causal: bool, block_q: int, block_k: int,
                    interpret: bool, with_lse: bool = False):
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    scale = D ** -0.5
    # Pad sequences to block multiples: in-kernel dynamic slices on a
    # non-multiple tail would clamp and silently re-read earlier rows.
    # Pad keys are masked in-kernel via seq_k; pad q rows are sliced off.
    Tq_p = block_q * ((Tq + block_q - 1) // block_q)
    Tk_p = block_k * ((Tk + block_k - 1) // block_k)
    if Tq_p != Tq:
        q = jnp.pad(q, ((0, 0), (0, Tq_p - Tq), (0, 0), (0, 0)))
    if Tk_p != Tk:
        k = jnp.pad(k, ((0, 0), (0, Tk_p - Tk), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Tk_p - Tk), (0, 0), (0, 0)))
    # Fold batch and heads into the grid's leading dim.
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq_p, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk_p, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk_p, D)
    grid = (B * H, Tq_p // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale, seq_k=Tk
    )
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, Tk_p, D), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, Tk_p, D), lambda b, i: (b, 0, 0)),
    ]
    o_shape = jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype)
    o_spec = pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0))
    if with_lse:
        out, lse = pl.pallas_call(
            kernel,
            out_shape=(
                o_shape,
                jax.ShapeDtypeStruct((B * H, 8, Tq_p), jnp.float32),
            ),
            grid=grid,
            in_specs=in_specs,
            out_specs=(
                o_spec,
                pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
            ),
            interpret=interpret,
        )(qf, kf, vf)
    else:
        # Inference/no-grad path: skip the LSE output entirely (it would be
        # pure wasted write bandwidth on every serving forward).
        out = pl.pallas_call(
            kernel,
            out_shape=o_shape,
            grid=grid,
            in_specs=in_specs,
            out_specs=o_spec,
            interpret=interpret,
        )(qf, kf, vf)
        lse = None
    out = out.reshape(B, H, Tq_p, D).transpose(0, 2, 1, 3)
    if Tq_p != Tq:
        out = out[:, :Tq]
    if with_lse:
        return out, lse  # lse stays in [B*H, Tq_p] layout for the backward
    return out


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float, seq_q: int, seq_k: int):
    """One (batch*head, k_block) program: accumulate dK/dV for this key
    block by streaming Q/dO blocks. Shapes: k/v/dk/dv [1, Bk, D];
    q/do [1, Tq, D]; lse/delta [1, 8, Tq] (row 0 is the data; the 8 rows
    are sublane replication for mosaic's block-shape rules)."""
    k_idx = pl.program_id(1)
    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    # bf16 operands + f32 accumulation on every dot (see _flash_kernel).
    k = k_ref[0]  # [Bk, D]
    v = v_ref[0]

    num_q_blocks = pl.cdiv(seq_q, block_q)
    if causal:
        # Lowest Q block that can see this K block (earlier ones are fully
        # masked): first q with q_pos >= k_idx*block_k.
        start = (k_idx * block_k) // block_q
    else:
        start = 0

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = scale * jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32)
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (q_pos < seq_q) & (k_pos < seq_k)
        if causal:
            mask = mask & (q_pos >= k_pos)
        # exp(NEG_INF - lse) underflows to 0 for masked/pad rows; force it
        # for bit-exact zeros.
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)  # [Bq, Bk]
        pcast = p.astype(do_blk.dtype)
        dv_new = dv_acc + jnp.dot(pcast.T, do_blk,
                                  preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk_acc + jnp.dot(ds.astype(q_blk.dtype).T, q_blk,
                                  preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool, scale: float,
                         seq_k: int):
    """One (batch*head, q_block) program: accumulate dQ for this query block
    by streaming K/V blocks. Shapes: q/do/dq [1, Bq, D]; k/v [1, Tk, D];
    lse/delta [1, 8, Bq] (row 0 is the data)."""
    q_idx = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    # bf16 operands + f32 accumulation on every dot (see _flash_kernel).
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]

    num_k_blocks = pl.cdiv(seq_k, block_k)
    if causal:
        last_block = ((q_idx + 1) * block_q - 1) // block_k + 1
        num_iter = jnp.minimum(num_k_blocks, last_block)
    else:
        num_iter = num_k_blocks

    def body(i, dq_acc):
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = scale * jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_k
        if causal:
            mask = mask & (q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq_acc + jnp.dot(ds.astype(k_blk.dtype), k_blk,
                                preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_iter, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_impl(q, k, v, out, lse, g, *, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    """Pallas flash backward: no [Tq, Tk] materialization (reference-free
    design; same tiling as FlashAttention-2). Returns (dq, dk, dv) with
    GQA head-group reduction applied."""
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    rep = H // Hkv
    if rep != 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    scale = D ** -0.5
    Tq_p = block_q * ((Tq + block_q - 1) // block_q)
    Tk_p = block_k * ((Tk + block_k - 1) // block_k)
    if Tq_p != Tq:
        q = jnp.pad(q, ((0, 0), (0, Tq_p - Tq), (0, 0), (0, 0)))
        g = jnp.pad(g, ((0, 0), (0, Tq_p - Tq), (0, 0), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, Tq_p - Tq), (0, 0), (0, 0)))
    if Tk_p != Tk:
        k = jnp.pad(k, ((0, 0), (0, Tk_p - Tk), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Tk_p - Tk), (0, 0), (0, 0)))
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq_p, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk_p, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk_p, D)
    dof = g.transpose(0, 2, 1, 3).reshape(B * H, Tq_p, D)
    of = out.transpose(0, 2, 1, 3).reshape(B * H, Tq_p, D)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise reduce in XLA,
    # replicated to the same [B*H, 8, Tq] sublane layout as lse.
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (B * H, 8, Tq_p))

    dkv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, causal=causal,
            scale=scale, seq_q=Tq, seq_k=Tk,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, Tk_p, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tk_p, D), q.dtype),
        ),
        grid=(B * H, Tk_p // block_k),
        in_specs=[
            pl.BlockSpec((1, Tq_p, D), lambda b, j: (b, 0, 0)),   # q
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),  # k
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),  # v
            pl.BlockSpec((1, Tq_p, D), lambda b, j: (b, 0, 0)),   # do
            pl.BlockSpec((1, 8, Tq_p), lambda b, j: (b, 0, 0)),   # lse
            pl.BlockSpec((1, 8, Tq_p), lambda b, j: (b, 0, 0)),   # delta
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
        ),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    dk, dv = dkv

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=block_k, causal=causal,
            scale=scale, seq_k=Tk,
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        grid=(B * H, Tq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),  # q
            pl.BlockSpec((1, Tk_p, D), lambda b, i: (b, 0, 0)),   # k
            pl.BlockSpec((1, Tk_p, D), lambda b, i: (b, 0, 0)),   # v
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),  # do
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),  # lse
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),  # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dq = dq.reshape(B, H, Tq_p, D).transpose(0, 2, 1, 3)[:, :Tq]
    dk = dk.reshape(B, H, Tk_p, D).transpose(0, 2, 1, 3)[:, :Tk]
    dv = dv.reshape(B, H, Tk_p, D).transpose(0, 2, 1, 3)[:, :Tk]
    if rep != 1:
        dk = dk.reshape(B, Tk, Hkv, rep, D).sum(axis=3)
        dv = dv.reshape(B, Tk, Hkv, rep, D).sum(axis=3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """Flash attention: pallas forward AND pallas backward (LSE saved by
    the forward; backward never materializes the [Tq, Tk] score matrix —
    round 2 recomputed attention in XLA for grads, which put three dense
    [B, H, Tq, Tk] tensors back into every train step)."""
    return _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, with_lse=True,
    )
    # Named so remat policies can keep them: without this, a jax.checkpoint
    # around the transformer block re-runs the flash forward a second time
    # in the backward pass just to rebuild these residuals.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(
        q, k, v, out, lse, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)


_PALLAS_OK = None


def _trace_state_clean() -> bool:
    """True when no jax trace is ambient (safe to execute eagerly)."""
    try:
        from jax._src import core as _core

        return isinstance(_core.trace_ctx.trace, _core.EvalTrace)
    except Exception:
        return False


def pallas_available() -> bool:
    """Whether pallas kernels can actually lower on this backend. A backend
    may report "tpu" yet lack mosaic lowering (e.g. remote-tunnel device
    plugins); "auto" must then fall back to XLA attention rather than fail
    at compile time. Probed once with a tiny kernel."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            # The dispatcher runs inside model jit traces, where an inner
            # jit call is inlined and returns a tracer — the round-2 probe
            # mis-diagnosed every backend as pallas-less (AttributeError on
            # tracer.block_until_ready; flash silently disabled). AOT
            # lower+compile traces the kernel fresh, independent of ambient
            # trace state, and exercises the mosaic lowering that decides
            # availability. Outside any trace, also run it for real.
            spec = jax.ShapeDtypeStruct((1, 128, 1, 128), jnp.float32)
            fn = jax.jit(
                lambda q: flash_attention(q, q, q, True, 128, 128, False)
            )
            compiled = fn.lower(spec).compile()
            if _trace_state_clean():
                out = compiled(jnp.zeros(spec.shape, spec.dtype))
                jax.block_until_ready(out)
            _PALLAS_OK = True
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "pallas unavailable on this backend (%s: %s); "
                "attention_impl='auto' falls back to XLA for this process",
                type(e).__name__, e,
            )
            _PALLAS_OK = False
    return _PALLAS_OK


def attention(
    q, k, v, *, causal: bool = True, impl: str = "auto",
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
):
    """Dispatcher. impl: auto | xla | flash | flash_interpret."""
    if impl == "auto":
        impl = (
            "flash"
            if jax.default_backend() == "tpu" and pallas_available()
            else "xla"
        )
    if impl == "xla":
        return attention_xla(q, k, v, causal=causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal, block_q, block_k, False)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal, block_q, block_k, True)
    raise ValueError(f"unknown attention impl {impl}")
