"""Dashboard-lite: HTTP observability + job REST endpoints on the head.

Reference analog: ``python/ray/dashboard/`` (head.py aiohttp app + modules:
node, actor, job, state). Round-1 scope: the JSON API surface (no web UI) —
enough for operators and the CLI/SDK to inspect nodes, actors, placement
groups, jobs, tasks, and autoscaler-relevant load, plus REST job
submit/stop (``dashboard/modules/job/job_head.py`` analog).
"""
from __future__ import annotations

import json
from typing import Optional


class DashboardApp:
    """Runs inside the head process; calls HeadService handlers directly."""

    def __init__(self, head, host: str = "127.0.0.1", port: int = 0):
        self.head = head
        self._host = host
        self._port = port
        self._runner = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        r = app.router
        r.add_get("/", self._index)
        r.add_get("/api/version", self._version)
        r.add_get("/api/nodes", self._nodes)
        r.add_get("/api/actors", self._actors)
        r.add_get("/api/placement_groups", self._pgs)
        r.add_get("/api/jobs", self._jobs)
        r.add_post("/api/jobs", self._submit_job)
        r.add_get("/api/jobs/{submission_id}", self._job_status)
        r.add_get("/api/jobs/{submission_id}/logs", self._job_logs)
        r.add_post("/api/jobs/{submission_id}/stop", self._stop_job)
        r.add_get("/api/tasks", self._tasks)
        r.add_get("/api/objects", self._objects)
        r.add_get("/api/cluster_status", self._cluster_status)
        r.add_get("/api/stacks", self._stacks)
        r.add_get("/api/logs", self._logs)
        r.add_get("/api/events", self._events)
        r.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        return self._port

    @property
    def port(self) -> int:
        return self._port

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()

    # ------------------------------------------------------------ handlers

    async def _head(self, method: str, header: dict):
        h, frames = await getattr(self.head, "rpc_" + method)(header, [], None)
        return h, frames

    async def _version(self, request):
        from aiohttp import web

        return web.json_response({"ray_tpu": "0.1", "api": "v1"})

    async def _nodes(self, request):
        from aiohttp import web

        h, _ = await self._head("get_nodes", {})
        return web.json_response(h)

    async def _actors(self, request):
        from aiohttp import web

        h, _ = await self._head("list_actors", {})
        return web.json_response(h)

    async def _pgs(self, request):
        from aiohttp import web

        h, _ = await self._head("list_pgs", {})
        return web.json_response(h)

    async def _jobs(self, request):
        from aiohttp import web

        h, _ = await self._head("list_jobs", {})
        return web.json_response(h)

    async def _submit_job(self, request):
        from aiohttp import web

        try:
            payload = json.loads(await request.read())
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        if "entrypoint" not in payload:
            return web.json_response(
                {"error": "entrypoint required"}, status=400
            )
        h, _ = await self._head("submit_job", payload)
        return web.json_response(h)

    async def _job_status(self, request):
        from aiohttp import web

        sid = request.match_info["submission_id"]
        h, _ = await self._head("job_status", {"submission_id": sid})
        if not h.get("found"):
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(h["job"])

    async def _job_logs(self, request):
        from aiohttp import web

        sid = request.match_info["submission_id"]
        h, frames = await self._head("job_logs", {"submission_id": sid})
        if not h.get("found"):
            return web.json_response({"error": "not found"}, status=404)
        text = bytes(frames[0]).decode(errors="replace") if frames else ""
        return web.json_response({"logs": text})

    async def _stop_job(self, request):
        from aiohttp import web

        sid = request.match_info["submission_id"]
        h, _ = await self._head("stop_job", {"submission_id": sid})
        return web.json_response(h)

    async def _events(self, request):
        """Structured export events (reference: the aggregator's event
        query surface) — filterable by source/event type."""
        from aiohttp import web

        try:
            limit = max(int(request.query.get("limit", "100")), 1)
        except ValueError:
            limit = 100
        h, _ = await self._head("export_events", {
            "limit": limit,
            "source_type": request.query.get("source_type"),
            "event_type": request.query.get("event_type"),
        })
        return web.json_response(h)

    async def _logs(self, request):
        from aiohttp import web

        try:
            tail = max(int(request.query.get("tail", "1000")), 0)
        except ValueError:
            tail = 1000
        h, _ = await self._head("get_logs", {
            "node_id": request.query.get("node_id"), "tail": tail,
        })
        return web.json_response(h)

    async def _tasks(self, request):
        from aiohttp import web

        limit = int(request.query.get("limit", 1000))
        h, _ = await self._head("list_task_events", {"limit": limit})
        return web.json_response(h)

    async def _objects(self, request):
        """Objects page data: the memory_summary fan-out joined head-side
        (object rows, per-node reconciliation, leak candidates). Query:
        ``group_by`` aggregates, ``grace`` tunes the leak window."""
        from aiohttp import web

        from ray_tpu._private import memtrack

        try:
            grace = float(request.query.get("grace", "5"))
        except ValueError:
            grace = 5.0
        h, _ = await self._head("memory_summary", {})
        summary = memtrack.build_summary(h, grace_s=grace)
        group_by = request.query.get("group_by")
        if group_by in memtrack.GROUP_KEYS:
            summary["groups"] = memtrack.group_rows(
                summary["rows"], group_by
            )
            summary["group_by"] = group_by
        return web.json_response(summary)

    async def _cluster_status(self, request):
        from aiohttp import web

        h, _ = await self._head("cluster_load", {})
        return web.json_response(h)

    async def _stacks(self, request):
        """Per-node all-thread stack dumps (reference: the reporter agent's
        py-spy profiling endpoint; see util/debug.py)."""
        from aiohttp import web

        h, _ = await self._head("cluster_stacks", {})
        return web.json_response(h)

    async def _index(self, request):
        """The web UI (reference: dashboard/client React app — here a
        dependency-free page over the same JSON API)."""
        from aiohttp import web

        from ray_tpu.dashboard.ui import INDEX_HTML

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _metrics(self, request):
        """Prometheus exposition (reference: metrics agent scrape target):
        user-defined series pushed by workers, head-derived cluster series
        (nodes/actors/demands/task counters), and the cluster-wide task
        phase rollup — ``rt_task_phase_seconds{phase,fn,node_id}``
        aggregated across every worker so ONE scrape covers every node
        (the serve autoscaler's and the chaos matrix's single source)."""
        from aiohttp import web

        from ray_tpu.util.metrics import (
            render_prometheus,
            rollup_gauge,
            rollup_histogram,
        )

        # Node-level rollup series: per-worker copies are excluded from
        # the plain rendering so sums over the scrape never double-count.
        ROLLUP_HIST = ("rt_task_phase_seconds",)
        # Object-plane gauges roll up per node too: "sum" for
        # owner-attributed series, "max" for node-shared readings every
        # process reports identically (arena counters, memory pressure).
        ROLLUP_GAUGE = {
            "rt_object_store_bytes": "sum",
            "rt_object_count": "sum",
            "rt_spill_bytes_total": "sum",
            "rt_restore_bytes_total": "sum",
            "rt_arena_graveyard_segments": "sum",
            "rt_arena_graveyard_bytes": "sum",
            "rt_arena_bytes": "max",
            "rt_node_memory_used_ratio": "max",
        }
        exclude = ROLLUP_HIST + tuple(ROLLUP_GAUGE)
        h, _ = await self._head("metrics_snapshot", {})
        snaps = h["snapshots"]
        text = render_prometheus(snaps, exclude=exclude)
        rollup = "".join(
            rollup_histogram(snaps, name, h.get("nodes"))
            for name in ROLLUP_HIST
        ) + "".join(
            rollup_gauge(snaps, name, h.get("nodes"), agg=agg)
            for name, agg in ROLLUP_GAUGE.items()
        )
        builtin = []
        for name, value in self.head.builtin_metrics().items():
            kind = "counter" if name.endswith("_total") else "gauge"
            builtin.append(f"# TYPE {name} {kind}")
            builtin.append(f"{name} {value}")
        return web.Response(
            text=text + rollup + "\n" + "\n".join(builtin) + "\n",
            content_type="text/plain",
        )
