"""Single-page dashboard UI (reference: ``python/ray/dashboard/client`` —
the reference ships a built React app; this is a dependency-free HTML page
that polls the same JSON API the CLI/SDK use, rendering live cluster state:
nodes, resource utilization, actors, placement groups, jobs, and task
summary)."""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { color-scheme: dark; }
  body { font-family: ui-monospace, Menlo, Consolas, monospace;
         background: #101318; color: #d7dce2; margin: 0; }
  header { padding: 10px 18px; background: #161b23;
           border-bottom: 1px solid #242b36; display: flex; gap: 18px;
           align-items: baseline; }
  h1 { font-size: 15px; margin: 0; color: #7fd1b9; }
  header span { color: #8b95a4; font-size: 12px; }
  main { padding: 14px 18px; display: grid; gap: 18px; }
  section h2 { font-size: 13px; color: #9fb6d0; margin: 0 0 6px;
               text-transform: uppercase; letter-spacing: .08em; }
  table { border-collapse: collapse; width: 100%; font-size: 12px; }
  th, td { text-align: left; padding: 4px 10px 4px 0;
           border-bottom: 1px solid #1d232d; }
  th { color: #6f7a89; font-weight: normal; }
  .ok { color: #7fd1b9; } .bad { color: #e07a7a; }
  .bar { display: inline-block; height: 8px; background: #2c6d5c;
         vertical-align: middle; border-radius: 2px; }
  .barbg { display: inline-block; width: 120px; height: 8px;
           background: #20262f; border-radius: 2px; margin-right: 6px; }
  a { color: #7fb3d1; }
  footer { color: #525c68; font-size: 11px; padding: 8px 18px; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span id="meta">loading…</span>
  <span><a href="/metrics">prometheus /metrics</a></span>
  <span><a href="/api/cluster_status">cluster_status.json</a></span>
</header>
<main>
  <section><h2>Nodes</h2><div id="nodes"></div></section>
  <section><h2>Actors</h2><div id="actors"></div></section>
  <section><h2>Placement groups</h2><div id="pgs"></div></section>
  <section><h2>Jobs</h2><div id="jobs"></div></section>
  <section><h2>Tasks (recent)</h2><div id="tasks"></div></section>
  <section><h2>Objects &amp; memory</h2><div id="objects"></div></section>
  <section><h2>Worker logs (recent)</h2><div id="logs"></div></section>
</main>
<footer>auto-refreshes every 2s · JSON API under /api/*</footer>
<script>
const $ = id => document.getElementById(id);
const esc = s => String(s ?? "").replace(/[&<>]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
function table(rows, cols) {
  if (!rows.length) return "<i>none</i>";
  let h = "<table><tr>" + cols.map(c => `<th>${c[0]}</th>`).join("") +
          "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c => `<td>${c[1](r)}</td>`).join("") + "</tr>";
  return h + "</table>";
}
function util(res, avail) {
  return Object.keys(res || {}).sort().map(k => {
    const total = res[k], free = (avail || {})[k] ?? total;
    const used = Math.max(total - free, 0);
    const pct = total > 0 ? Math.round(100 * used / total) : 0;
    return `${esc(k)} <span class=barbg><span class=bar style="width:` +
           `${1.2 * pct}px"></span></span>${used.toFixed(1)}/${total}`;
  }).join("<br>");
}
async function j(url) { const r = await fetch(url); return r.json(); }
function mb(n) { return (n / 1048576).toFixed(2) + " MiB"; }
async function refresh() {
  try {
    const [nodes, actors, pgs, jobs, tasks, logs, objs] = await Promise.all([
      j("/api/nodes"), j("/api/actors"), j("/api/placement_groups"),
      j("/api/jobs"), j("/api/tasks"), j("/api/logs?tail=100"),
      j("/api/objects")]);
    const ns = nodes.nodes || [];
    $("meta").textContent =
      `${ns.filter(n => n.alive).length} alive node(s), ` +
      `${(actors.actors || []).length} actor(s)`;
    $("nodes").innerHTML = table(ns, [
      ["node", n => esc(n.node_id.slice(0, 10))],
      ["state", n => n.alive ? '<span class=ok>ALIVE</span>'
                             : '<span class=bad>DEAD</span>'],
      ["addr", n => esc((n.addr || []).join(":"))],
      ["utilization", n => util(n.resources, n.available)],
      ["labels", n => esc(JSON.stringify(n.labels || {}))]]);
    $("actors").innerHTML = table(actors.actors || [], [
      ["actor", a => esc(a.actor_id.slice(0, 10))],
      ["class", a => esc(a.class_name)],
      ["name", a => esc(a.name || "")],
      ["state", a => a.state === "ALIVE"
        ? '<span class=ok>ALIVE</span>' : esc(a.state)],
      ["restarts", a => a.restarts_used],
      ["node", a => esc((a.node_id || "").slice(0, 10))]]);
    $("pgs").innerHTML = table(pgs.pgs || [], [
      ["pg", p => esc(p.placement_group_id.slice(0, 10))],
      ["name", p => esc(p.name || "")],
      ["strategy", p => esc(p.strategy)],
      ["state", p => esc(p.state)],
      ["bundles", p => esc(JSON.stringify(p.bundles))]]);
    $("jobs").innerHTML = table(jobs.jobs || [], [
      ["job", x => esc(x.job_id || x.submission_id || "")],
      ["state", x => esc(x.state || x.status || "")],
      ["started", x => x.start_time
        ? new Date(x.start_time * 1000).toLocaleTimeString() : ""]]);
    const ts = (tasks.events || []).slice(-25).reverse();
    $("tasks").innerHTML = table(ts, [
      ["task", t => esc((t.task_id || "").slice(0, 10))],
      ["name", t => esc(t.name || "")],
      ["type", t => esc(t.type || "")],
      ["state", t => t.state === "FINISHED"
        ? '<span class=ok>FINISHED</span>'
        : (t.state === "FAILED" ? '<span class=bad>FAILED</span>'
                                : esc(t.state))],
      ["node", t => esc((t.node_id || "").slice(0, 10))]]);
    const t = objs.totals || {};
    const leaks = objs.leaks || [];
    const head =
      `objects: ${t.objects ?? 0} · inline ${mb(t.inline_bytes || 0)}` +
      ` · shm ${mb(t.shm_bytes || 0)} · spilled ${mb(t.spilled_bytes || 0)}` +
      ` · directory ${t.directory_entries ?? 0}` +
      (leaks.length
        ? ` · <span class=bad>${leaks.length} leak candidate(s)</span>`
        : ' · <span class=ok>no leaks</span>');
    const rows = (objs.rows || [])
      .slice().sort((a, b) => (b.bytes || 0) - (a.bytes || 0)).slice(0, 15);
    $("objects").innerHTML = `<p>${head}</p>` + table(rows, [
      ["object", o => esc((o.oid || "").slice(0, 10))],
      ["kind", o => esc(o.kind || "")],
      ["state", o => o.state === "pinned"
        ? '<span class=ok>pinned</span>' : esc(o.state || "")],
      ["bytes", o => mb(o.bytes || 0)],
      ["node", o => esc((o.node || "").slice(0, 10))],
      ["fn", o => esc(o.fn || "")],
      ["task", o => esc((o.task || "").slice(0, 10))]]);
    const ls = (logs.lines || []).slice(-40);
    $("logs").innerHTML = ls.length
      ? "<pre>" + ls.map(l =>
          `(pid=${esc(l.pid)}, node=${esc((l.node_id || "").slice(0, 8))}` +
          `, ${esc(l.stream)}) ${esc(l.line)}`).join("\n") + "</pre>"
      : "<i>none</i>";
  } catch (e) {
    $("meta").textContent = "refresh failed: " + e;
  }
}
refresh(); setInterval(refresh, 2000);
</script>
</body>
</html>
"""
