from ray_tpu.dashboard.app import DashboardApp

__all__ = ["DashboardApp"]
