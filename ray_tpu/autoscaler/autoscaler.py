"""Autoscaler v2: demand-driven reconciliation of cluster nodes.

Reference analog: ``python/ray/autoscaler/v2/autoscaler.py:51`` —
``update_autoscaling_state`` (:181) reads pending demand from GCS
(``gcs_autoscaler_state_manager.cc``), bin-packs it against node types
(``scheduler.py:476 try_schedule``), and drives an instance-manager
reconciler over cloud nodes. Same loop here, sized for the process-per-host
model: demand = unsatisfied lease waits + pending PG bundles; supply =
per-node available resources; delta = nodes to launch / idle nodes to drain.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 60.0
    upscaling_speed: int = 100  # max nodes launched per update


# Same epsilon as the head's scheduler (_fits in _private/gcs.py): float
# residue from fractional acquire/release must not diverge the two views.
def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


def _is_idle(node: dict) -> bool:
    res, avail = node["resources"], node["available"]
    return all(abs(avail.get(k, 0.0) - v) < 1e-6 for k, v in res.items())


def _sub(avail: Dict[str, float], need: Dict[str, float]):
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    def __init__(self, head_address: str, config: AutoscalerConfig,
                 provider: NodeProvider):
        from ray_tpu._private.sync_client import SyncHeadClient

        self.config = config
        self.provider = provider
        self._client = SyncHeadClient(head_address)
        self._idle_since: Dict[str, float] = {}  # cluster node_id -> ts
        # node_ids this autoscaler has ever seen alive: a provider instance
        # whose node registered and later vanished from the head's view is a
        # phantom even if its dead-node tombstone was evicted from the
        # head's bounded cache (gcs.py dead_nodes). Absence must persist for
        # several passes before termination — a restarting head briefly
        # reports nothing while nodes re-register, and killing healthy
        # instances on that window would be self-inflicted failure.
        self._ever_alive: set = set()
        self._missing_counts: Dict[str, int] = {}
        self._MISSING_PASSES = 3

    # ---------------------------------------------------------------- update

    def update(self) -> dict:
        """One reconcile pass; returns {launched: {type: n}, terminated: [..]}."""
        load, _ = self._client.call("cluster_load", {})
        demands: List[Dict[str, float]] = []
        for d in load["pending"]:
            # one waiter may represent many unsatisfied bundles
            demands.extend([d["resources"]] * max(int(d.get("count", 1)), 1))
        for pg in load["pending_pgs"]:
            demands.extend(pg["bundles"])

        # simulated free capacity: live registered nodes' available, PLUS the
        # full resources of provider nodes still booting (launched earlier,
        # not yet in the head's view) — without that credit every reconcile
        # pass would re-launch for the same demand until registration.
        alive_ids = {
            n["node_id"] for n in load["nodes"] if n.get("alive")
        }
        self._ever_alive |= alive_ids
        dead_ids = {
            n["node_id"] for n in load["nodes"] if not n.get("alive")
        }
        sim: List[Dict[str, float]] = [
            dict(n["available"]) for n in load["nodes"] if n.get("alive")
        ]
        provider_nodes = self.provider.non_terminated_nodes()
        # Bound _ever_alive: once a provider instance is gone its id can
        # never match the phantom check again, so only ids still backing a
        # provider node need remembering.
        provider_ids = {
            n.get("node_id") for n in provider_nodes if n.get("node_id")
        }
        self._ever_alive &= provider_ids | alive_ids
        by_type: Dict[str, int] = {}
        for n in provider_nodes:
            node_id = n.get("node_id")
            missing = (
                node_id in self._ever_alive and node_id not in alive_ids
                and node_id not in dead_ids
            )
            if missing:
                self._missing_counts[node_id] = (
                    self._missing_counts.get(node_id, 0) + 1
                )
            else:
                self._missing_counts.pop(node_id, None)
            if node_id in dead_ids or (
                missing
                and self._missing_counts[node_id] >= self._MISSING_PASSES
            ):
                # registered then died: phantom — reclaim, never credit.
                # The _ever_alive path survives tombstone-cache eviction but
                # requires sustained absence (head-restart tolerance).
                self.provider.terminate_node(n["provider_node_id"])
                continue
            by_type[n["node_type"]] = by_type.get(n["node_type"], 0) + 1
            if node_id not in alive_ids:
                # launched, not yet registered: credit full resources so the
                # same demand doesn't trigger a duplicate launch
                tcfg = self.config.node_types.get(n["node_type"])
                if tcfg is not None:
                    sim.append(dict(tcfg.resources))

        launched: Dict[str, int] = {}
        budget = self.config.upscaling_speed

        # min_workers floor
        for tname, tcfg in self.config.node_types.items():
            while by_type.get(tname, 0) < tcfg.min_workers and budget > 0:
                self._launch(tname, tcfg, launched, by_type, sim)
                budget -= 1

        # Bin-pack demands into simulated capacity, else launch the
        # smallest node type that can hold the bundle (reference:
        # v2/scheduler.py try_schedule). First-fit-DECREASING: placing the
        # big shapes first lets the small ones fill the leftovers — the
        # unsorted order can strand a large bundle on a fresh node whose
        # remainder the earlier small demands would have used.
        demands.sort(key=lambda d: sum(d.values()), reverse=True)
        for need in demands:
            placed = False
            for avail in sim:
                if _fits(avail, need):
                    _sub(avail, need)
                    placed = True
                    break
            if placed or budget <= 0:
                continue
            candidates = sorted(
                (
                    (tname, tcfg)
                    for tname, tcfg in self.config.node_types.items()
                    if _fits(tcfg.resources, need)
                    and by_type.get(tname, 0) < tcfg.max_workers
                ),
                key=lambda tc: sum(tc[1].resources.values()),
            )
            if not candidates:
                logger.warning("autoscaler: demand %s fits no node type", need)
                continue
            tname, tcfg = candidates[0]
            avail = self._launch(tname, tcfg, launched, by_type, sim)
            _sub(avail, need)
            budget -= 1

        terminated = self._scale_down(load, provider_nodes)
        return {"launched": launched, "terminated": terminated}

    def _launch(self, tname, tcfg, launched, by_type, sim):
        self.provider.create_node(tname, tcfg.resources, tcfg.labels)
        launched[tname] = launched.get(tname, 0) + 1
        by_type[tname] = by_type.get(tname, 0) + 1
        avail = dict(tcfg.resources)
        sim.append(avail)
        return avail

    def _scale_down(self, load, provider_nodes) -> List[str]:
        """Terminate provider-owned nodes idle past the timeout (never below
        min_workers)."""
        now = time.monotonic()
        alive = {n["node_id"]: n for n in load["nodes"] if n.get("alive")}
        by_type: Dict[str, int] = {}
        for n in provider_nodes:
            by_type[n["node_type"]] = by_type.get(n["node_type"], 0) + 1
        terminated = []
        for pn in provider_nodes:
            info = alive.get(pn["node_id"])
            if info is None:
                continue
            idle = _is_idle(info)
            if not idle:
                self._idle_since.pop(pn["node_id"], None)
                continue
            since = self._idle_since.setdefault(pn["node_id"], now)
            tcfg = self.config.node_types.get(pn["node_type"])
            floor = tcfg.min_workers if tcfg else 0
            if (now - since > self.config.idle_timeout_s
                    and by_type.get(pn["node_type"], 0) > floor):
                try:
                    self._client.call(
                        "drain_node", {"node_id": pn["node_id"]}
                    )
                except Exception as e:
                    # Best-effort: the node is being terminated either
                    # way, but a dropped drain should be diagnosable.
                    logger.debug("drain_node %s failed: %s",
                                 pn["node_id"], e)
                self.provider.terminate_node(pn["provider_node_id"])
                by_type[pn["node_type"]] -= 1
                terminated.append(pn["provider_node_id"])
                self._idle_since.pop(pn["node_id"], None)
        return terminated

    def close(self):
        self._client.close()


class AutoscalerMonitor:
    """Background loop driving Autoscaler.update (reference:
    ``autoscaler/v2/monitor.py``)."""

    def __init__(self, autoscaler: Autoscaler, interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="rt-autoscaler"
        )
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler update failed")
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
