"""Cluster launcher: a YAML file → a running cluster.

Reference analogs: ``python/ray/scripts/scripts.py:799`` (``ray up``) +
``python/ray/autoscaler/_private/commands.py`` (create_or_update_cluster /
teardown_cluster) and the cluster-YAML schema (provider section, available
node types with min/max workers). TPU-era differences: the "monitor"
(autoscaler) runs as a plain subprocess next to the head rather than inside
it, providers are the thin ABC in ``node_provider.py`` (local subprocess
nodes for dev boxes/CI, gcloud TPU VMs for real pods), and cluster state is
one JSON file per cluster name under ``~/.ray_tpu``.

YAML schema::

    cluster_name: demo
    provider:
      type: local            # local | gce_tpu
      # gce_tpu: project, zone, accelerator_type, version
    head:
      num_cpus: 4
      port: 0                # 0 = pick a free port
      dashboard_port: -1     # -1 = disabled
    node_types:
      worker:
        resources: {CPU: 4}
        min_workers: 1
        max_workers: 8
    idle_timeout_s: 60
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, Optional

from ray_tpu._private.backoff import Backoff

from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    AutoscalerMonitor,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.node_provider import (
    GCETPUNodeProvider,
    KubernetesNodeProvider,
    LocalNodeProvider,
    NodeProvider,
)

logger = logging.getLogger(__name__)


def _state_dir() -> str:
    d = os.environ.get("RT_CLUSTER_STATE_DIR") or os.path.join(
        os.path.expanduser("~"), ".ray_tpu"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _state_path(name: str) -> str:
    return os.path.join(_state_dir(), f"cluster_{name}.json")


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if "cluster_name" not in cfg:
        raise ValueError("cluster YAML needs cluster_name")
    cfg.setdefault("provider", {"type": "local"})
    cfg.setdefault("head", {})
    cfg.setdefault("node_types", {})
    for name, nt in cfg["node_types"].items():
        if "resources" not in nt:
            raise ValueError(f"node_type {name!r} needs resources")
    return cfg


def build_provider(cfg: Dict[str, Any], head_address: str) -> NodeProvider:
    p = cfg["provider"]
    kind = p.get("type", "local")
    if kind == "local":
        return LocalNodeProvider(head_address)
    if kind == "gce_tpu":
        return GCETPUNodeProvider(
            head_address,
            project=p["project"], zone=p["zone"],
            # per-node-type config (accelerator_type etc.) comes from the
            # YAML node_types section — the provider maps each type to a
            # TPU slice shape
            node_types={
                name: dict(nt) for name, nt in cfg["node_types"].items()
            },
            version=p.get("version", "tpu-ubuntu2204-base"),
        )
    if kind == "kubernetes":
        return KubernetesNodeProvider(
            head_address,
            namespace=p.get("namespace", "default"),
            cluster_name=p.get("cluster_name", "raytpu"),
            node_types={
                name: dict(nt) for name, nt in cfg["node_types"].items()
            },
            image=p.get("image", "python:3.12-slim"),
        )
    raise ValueError(f"unknown provider type {kind!r}")


def autoscaler_config(cfg: Dict[str, Any]) -> AutoscalerConfig:
    return AutoscalerConfig(
        node_types={
            name: NodeTypeConfig(
                resources={k: float(v) for k, v in nt["resources"].items()},
                min_workers=int(nt.get("min_workers", 0)),
                max_workers=int(nt.get("max_workers", 10)),
                labels=nt.get("labels", {}) or {},
            )
            for name, nt in cfg["node_types"].items()
        },
        idle_timeout_s=float(cfg.get("idle_timeout_s", 60.0)),
        upscaling_speed=int(cfg.get("upscaling_speed", 100)),
    )


def up(path: str, *, wait_for_min_workers: float = 0.0) -> Dict[str, Any]:
    """Start head + autoscaler monitor for the YAML cluster; returns the
    recorded cluster state {address, head_pid, monitor_pid, ...}."""
    cfg = load_cluster_config(path)
    name = cfg["cluster_name"]
    state_file = _state_path(name)
    if os.path.exists(state_file):
        prev = json.load(open(state_file))
        if _pid_alive(prev.get("head_pid")):
            raise RuntimeError(
                f"cluster {name!r} already running at {prev['address']} "
                f"(use `rt down {path}` first)"
            )
        # Head died but a monitor may survive: stop it (it tears down its
        # provider nodes on SIGTERM) before discarding the state — unlinking
        # first would orphan the monitor and every node it launched.
        mon = prev.get("monitor_pid")
        if _pid_alive(mon):
            try:
                os.kill(mon, signal.SIGTERM)
            except OSError:
                pass
            deadline = time.monotonic() + 15
            poll = Backoff(base=0.05, cap=0.5)
            while time.monotonic() < deadline and _pid_alive(mon):
                poll.sleep()
            if _pid_alive(mon):
                try:
                    os.kill(mon, signal.SIGKILL)
                except OSError:
                    pass
        os.unlink(state_file)
    head = cfg["head"]
    log_dir = os.path.join(_state_dir(), "logs")
    os.makedirs(log_dir, exist_ok=True)
    info_file = os.path.join(_state_dir(), f"cluster_{name}.info.json")
    try:
        os.unlink(info_file)
    except OSError:
        pass
    cmd = [
        sys.executable, "-m", "ray_tpu._private.head_main",
        "--host", str(head.get("host", "127.0.0.1")),
        "--port", str(head.get("port", 0)),
        "--num-cpus", str(head.get("num_cpus", os.cpu_count() or 1)),
        "--resources", json.dumps(head.get("resources", {})),
        "--dashboard-port", str(head.get("dashboard_port", -1)),
        "--info-file", info_file,
        "--no-address-file",
    ]
    # Daemon children must NOT inherit the caller's stdio (an `rt up` whose
    # parent captures output would never see EOF on its pipes), and tasks
    # scheduled on the head-local node print through the inherited fds —
    # everything goes to the per-cluster log; the startup info arrives via
    # the atomically-published info file.
    head_log = open(os.path.join(log_dir, f"{name}-head.log"), "ab")
    proc = subprocess.Popen(
        cmd, stdout=head_log, stderr=head_log, stdin=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 60
    info = None
    poll = Backoff(base=0.02, cap=0.25)
    while time.monotonic() < deadline:
        if os.path.exists(info_file):
            try:
                info = json.load(open(info_file))
                break
            except json.JSONDecodeError:
                pass  # partially visible; retry
        if proc.poll() is not None:
            break
        poll.sleep()
    if info is None:
        proc.kill()
        raise RuntimeError(
            f"head failed to start (see {head_log.name})"
        )
    address = info["address"]
    # Adopt the head's auth token: the monitor subprocess, every node it
    # spawns, and this process's own head RPCs (min-worker wait, status)
    # all authenticate with it via the inherited env.
    from ray_tpu._private.auth import adopt_token

    adopt_token(info)
    mon_log = open(os.path.join(log_dir, f"{name}-monitor.log"), "ab")
    monitor = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu.autoscaler.launcher",
            "--monitor", "--config", os.path.abspath(path),
            "--address", address,
        ],
        stdout=mon_log, stderr=mon_log, stdin=subprocess.DEVNULL,
    )
    state = {
        "cluster_name": name,
        "address": address,
        "head_pid": proc.pid,
        "monitor_pid": monitor.pid,
        "config_path": os.path.abspath(path),
        "started_at": time.time(),
    }
    with open(state_file, "w") as f:
        json.dump(state, f)
    if wait_for_min_workers > 0:
        if not _wait_min_workers(cfg, address, timeout=wait_for_min_workers):
            import sys as _sys

            print(
                f"WARNING: min_workers did not register within "
                f"{wait_for_min_workers:.0f}s (see "
                f"{os.path.join(log_dir, name + '-monitor.log')})",
                file=_sys.stderr,
            )
    return state


def _wait_min_workers(cfg, address, timeout: float):
    from ray_tpu._private.sync_client import SyncHeadClient

    # The head-local node (spawned when head.num_cpus > 0, the default)
    # registers too and must not count toward min_workers.
    head_nodes = 1 if int(
        cfg["head"].get("num_cpus", os.cpu_count() or 1)
    ) > 0 else 0
    want = head_nodes + sum(
        int(nt.get("min_workers", 0)) for nt in cfg["node_types"].values()
    )
    deadline = time.monotonic() + timeout
    poll = Backoff(base=0.25, cap=2.0)
    while time.monotonic() < deadline:
        try:
            client = SyncHeadClient(address)
            h, _ = client.call("get_nodes", {})
            client.close()
            alive = sum(1 for n in h["nodes"] if n.get("alive"))
            if alive >= want:
                return True
        except Exception as e:
            logger.debug("get_nodes poll failed (head still coming up?): "
                         "%s", e)
        poll.sleep()
    return False


def _pid_alive(pid) -> bool:
    if not pid:
        return False
    try:
        # Reap first when it's our child: a kill'd-but-unreaped zombie
        # still answers kill(pid, 0).
        os.waitpid(pid, os.WNOHANG)
    except (ChildProcessError, OSError):
        pass
    try:
        # An unreapable zombie (child of some OTHER live process) still
        # answers kill(pid, 0); for liveness purposes it is dead.
        with open(f"/proc/{pid}/stat") as f:
            if f.read().rsplit(")", 1)[-1].split()[0] == "Z":
                return False
    except OSError:
        pass
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def down(path_or_name: str) -> bool:
    """Tear the cluster down: provider nodes, monitor, head."""
    if os.path.exists(path_or_name):
        name = load_cluster_config(path_or_name)["cluster_name"]
    else:
        name = path_or_name
    state_file = _state_path(name)
    if not os.path.exists(state_file):
        return False
    state = json.load(open(state_file))
    # The MONITOR owns provider-node cleanup (its SIGTERM handler tears the
    # launched nodes down — only its provider instance tracks them). Stop
    # it first and give it time to finish before touching the head.
    mon_pid = state.get("monitor_pid")
    if _pid_alive(mon_pid):
        try:
            os.kill(mon_pid, signal.SIGTERM)
        except OSError:
            pass
        deadline = time.monotonic() + 15
        poll = Backoff(base=0.05, cap=0.5)
        while time.monotonic() < deadline and _pid_alive(mon_pid):
            poll.sleep()
    head_pid = state.get("head_pid")
    if _pid_alive(head_pid):
        try:
            os.kill(head_pid, signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + 5
    poll = Backoff(base=0.05, cap=0.5)
    while time.monotonic() < deadline and (
        _pid_alive(state.get("head_pid"))
        or _pid_alive(state.get("monitor_pid"))
    ):
        poll.sleep()
    for key in ("monitor_pid", "head_pid"):
        pid = state.get(key)
        if _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    # SIGKILL delivery + reaping are asynchronous: wait until both pids are
    # really gone so `down()` returning means the cluster is down.
    deadline = time.monotonic() + 10
    poll = Backoff(base=0.02, cap=0.25)
    while time.monotonic() < deadline and (
        _pid_alive(state.get("head_pid"))
        or _pid_alive(state.get("monitor_pid"))
    ):
        poll.sleep()
    os.unlink(state_file)
    return True


def cluster_state(name: str) -> Optional[Dict[str, Any]]:
    p = _state_path(name)
    if not os.path.exists(p):
        return None
    return json.load(open(p))


def _monitor_main(config_path: str, address: str):
    """The autoscaler monitor process (reference: monitor.py next to the
    GCS): reconcile loop until SIGTERM, then terminate every provider node
    — the monitor's provider instance is the only holder of the launched
    node handles, so teardown MUST happen here (a fresh provider in
    ``down()`` would see an empty node table)."""
    cfg = load_cluster_config(config_path)
    provider = build_provider(cfg, address)
    autoscaler = Autoscaler(address, autoscaler_config(cfg), provider)
    runner = AutoscalerMonitor(autoscaler, interval_s=2.0)
    stop = {"flag": False}

    def term(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, term)
    runner.start()
    try:
        idle = Backoff(base=0.2, cap=1.0)
        while not stop["flag"]:
            idle.sleep()
    finally:
        runner.stop()
        for n in provider.non_terminated_nodes():
            try:
                provider.terminate_node(n["provider_node_id"])
            except Exception:
                pass


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--monitor", action="store_true")
    ap.add_argument("--config", required=True)
    ap.add_argument("--address", required=True)
    a = ap.parse_args()
    if a.monitor:
        _monitor_main(a.config, a.address)
