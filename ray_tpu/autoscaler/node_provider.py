"""Node providers: the autoscaler's cloud abstraction.

Reference analog: ``python/ray/autoscaler/node_provider.py`` (NodeProvider
ABC) + v2's instance manager cloud interface. ``LocalNodeProvider`` spawns
worker-node processes on this machine — the test/single-host provider the
reference implements as ``autoscaler/_private/fake_multi_node``; a GKE/TPU
provider implements the same three methods with cloud instance calls.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class NodeProvider(ABC):
    @abstractmethod
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        """Launch one node; returns a provider node id."""

    @abstractmethod
    def terminate_node(self, provider_node_id: str):
        ...

    @abstractmethod
    def non_terminated_nodes(self) -> List[dict]:
        """[{provider_node_id, node_type, node_id (cluster id, may be None)}]"""


class LocalNodeProvider(NodeProvider):
    """Spawns worker_main processes against a head address."""

    def __init__(self, head_address: str):
        host, _, port = head_address.rpartition(":")
        self._gcs_addr = (host or "127.0.0.1", int(port))
        self._nodes: Dict[str, dict] = {}
        self._counter = 0

    def create_node(self, node_type, resources, labels=None) -> str:
        from ray_tpu._private.ids import JobID
        from ray_tpu._private.node import spawn_node

        handle = spawn_node(
            self._gcs_addr, JobID.from_random(), dict(resources), labels
        )
        pid = f"local-{self._counter}"
        self._counter += 1
        self._nodes[pid] = {
            "provider_node_id": pid,
            "node_type": node_type,
            "node_id": handle.node_id,
            "handle": handle,
        }
        return pid

    def terminate_node(self, provider_node_id: str):
        info = self._nodes.pop(provider_node_id, None)
        if info is not None:
            info["handle"].terminate()

    def non_terminated_nodes(self) -> List[dict]:
        out = []
        for pid, info in list(self._nodes.items()):
            if info["handle"].alive():
                out.append({k: info[k] for k in
                            ("provider_node_id", "node_type", "node_id")})
            else:
                self._nodes.pop(pid, None)
        return out


def _cli_runner(args: List[str], stdin: Optional[str] = None,
                timeout: int = 600) -> str:
    """Shared subprocess runner for cloud CLIs (kubectl/gcloud)."""
    import subprocess

    res = subprocess.run(
        args, capture_output=True, text=True, timeout=timeout, input=stdin
    )
    if res.returncode != 0:
        raise RuntimeError(
            f"{' '.join(args[:4])}... failed: {res.stderr[-500:]}"
        )
    return res.stdout


class KubernetesNodeProvider(NodeProvider):
    """Kubernetes pod-per-node provider (reference analogs: the in-tree
    kubernetes NodeProvider, ``autoscaler/_private/kubernetes/
    node_provider.py``, which manipulates worker pods directly, and the
    KubeRay operator's pod templates). GKE TPU node pools expose chips as
    the ``google.com/tpu`` resource with slice topology via node selectors
    — a node type maps to a pod spec requesting them.

    ``runner`` injects the command executor (tests pass a fake; production
    uses subprocess + kubectl). No cluster calls at import or init.
    """

    def __init__(self, head_address: str, *, namespace: str = "default",
                 cluster_name: str = "raytpu",
                 node_types: Optional[Dict[str, dict]] = None,
                 image: str = "python:3.12-slim", runner=None):
        self._head_address = head_address
        self._namespace = namespace
        self._cluster = cluster_name
        # node_type -> {"resources": {...}, "pod_resources": {k8s requests},
        #               "node_selector": {...}, "image": optional override}
        self._node_types = dict(node_types or {})
        self._image = image
        self._runner = runner or _cli_runner
        self._counter = 0
        self._nodes: Dict[str, dict] = {}

    def _pod_manifest(self, name: str, node_type: str, tcfg: dict) -> dict:
        pod_resources = dict(tcfg.get("pod_resources") or {})
        container = {
            "name": "worker",
            "image": tcfg.get("image", self._image),
            "command": ["python", "-m", "ray_tpu.cli", "start",
                        "--address", self._head_address],
            "env": [
                # the cluster token rides a Secret, never the pod spec
                {"name": "RT_AUTH_TOKEN", "valueFrom": {"secretKeyRef": {
                    "name": f"{self._cluster}-auth", "key": "token",
                    "optional": True,
                }}},
            ],
        }
        if pod_resources:
            container["resources"] = {
                "requests": pod_resources, "limits": pod_resources,
            }
        spec = {"containers": [container], "restartPolicy": "Never"}
        if tcfg.get("node_selector"):
            spec["nodeSelector"] = dict(tcfg["node_selector"])
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": self._namespace,
                "labels": {
                    "raytpu.io/cluster": self._cluster,
                    "raytpu.io/node-type": node_type,
                },
            },
            "spec": spec,
        }

    def create_node(self, node_type, resources, labels=None) -> str:
        import json as _json

        tcfg = self._node_types.get(node_type, {})
        self._counter += 1
        name = f"{self._cluster}-{node_type}-{self._counter}"
        manifest = self._pod_manifest(name, node_type, tcfg)
        self._runner(
            ["kubectl", "-n", self._namespace, "apply", "-f", "-"],
            stdin=_json.dumps(manifest),
        )
        self._nodes[name] = {
            "provider_node_id": name,
            "node_type": node_type,
            "node_id": None,  # learned when the pod registers with the head
        }
        return name

    def terminate_node(self, provider_node_id: str):
        if provider_node_id not in self._nodes:
            return
        self._runner([
            "kubectl", "-n", self._namespace, "delete", "pod",
            provider_node_id, "--ignore-not-found", "--wait=false",
        ])
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[dict]:
        import json as _json

        out = self._runner([
            "kubectl", "-n", self._namespace, "get", "pods",
            "-l", f"raytpu.io/cluster={self._cluster}", "-o", "json",
        ])
        live = {}
        for pod in _json.loads(out or "{}").get("items", []):
            name = pod.get("metadata", {}).get("name", "")
            phase = pod.get("status", {}).get("phase")
            if name in self._nodes and phase in (
                "Pending", "Running", None
            ):
                live[name] = self._nodes[name]
            elif name in self._nodes or phase in ("Failed", "Succeeded"):
                # Terminal pods (restartPolicy=Never leaves the objects
                # behind) AND tracked pods in unexpected phases (Unknown —
                # partitioned kubelet) are reclaimed; dropping them from
                # tracking without deletion would leak quota forever
                try:
                    self._runner([
                        "kubectl", "-n", self._namespace, "delete", "pod",
                        name, "--ignore-not-found", "--wait=false",
                    ])
                except RuntimeError:
                    pass
        # drop records for pods that disappeared out from under us
        self._nodes = dict(live)
        return [
            {k: info[k] for k in
             ("provider_node_id", "node_type", "node_id")}
            for info in live.values()
        ]


class GCETPUNodeProvider(NodeProvider):
    """GCE TPU-VM provider (reference analogs: the GCP provider +
    ``autoscaler/tpu_command_runner.py`` / ``gcp/tpu.yaml``): scales the
    cluster by creating/deleting TPU VMs through ``gcloud compute tpus
    tpu-vm``. Each node type maps to an accelerator type (a whole slice —
    slices are the atomic scaling unit on TPU, not single hosts); a startup
    script joins the new VM to the head over DCN.

    ``runner`` injects the command executor (tests pass a fake; production
    uses subprocess + gcloud). No cloud calls happen at import or init.
    """

    def __init__(self, head_address: str, *, project: str, zone: str,
                 node_types: Optional[Dict[str, dict]] = None,
                 runner=None, version: str = "tpu-ubuntu2204-base"):
        self._head_address = head_address
        self._project = project
        self._zone = zone
        # node_type -> {"accelerator_type": "v5e-16", "resources": {...}}
        self._node_types = dict(node_types or {})
        self._version = version
        self._runner = runner or _cli_runner
        self._counter = 0
        self._nodes: Dict[str, dict] = {}

    def _startup_script(self) -> str:
        return (
            "#! /bin/bash\n"
            "python -m ray_tpu.cli start "
            f"--address {self._head_address}\n"
        )

    def create_node(self, node_type, resources, labels=None) -> str:
        tcfg = self._node_types.get(node_type, {})
        accel = tcfg.get("accelerator_type") or node_type
        self._counter += 1
        name = f"raytpu-{node_type}-{self._counter}"
        self._runner([
            "gcloud", "compute", "tpus", "tpu-vm", "create", name,
            "--project", self._project, "--zone", self._zone,
            "--accelerator-type", accel, "--version", self._version,
            "--metadata", f"startup-script={self._startup_script()}",
        ])
        self._nodes[name] = {
            "provider_node_id": name,
            "node_type": node_type,
            "node_id": None,  # learned when the VM registers with the head
        }
        return name

    def terminate_node(self, provider_node_id: str):
        if provider_node_id not in self._nodes:
            return
        self._runner([
            "gcloud", "compute", "tpus", "tpu-vm", "delete",
            provider_node_id, "--project", self._project,
            "--zone", self._zone, "--quiet",
        ])
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[dict]:
        import json as _json

        out = self._runner([
            "gcloud", "compute", "tpus", "tpu-vm", "list",
            "--project", self._project, "--zone", self._zone,
            "--format", "json",
        ])
        live = {}
        for vm in _json.loads(out or "[]"):
            name = vm.get("name", "").rsplit("/", 1)[-1]
            if name in self._nodes and vm.get("state") in (
                "READY", "CREATING", None
            ):
                live[name] = self._nodes[name]
        # drop records for VMs that disappeared out from under us
        self._nodes = dict(live)
        return [
            {k: info[k] for k in
             ("provider_node_id", "node_type", "node_id")}
            for info in live.values()
        ]
