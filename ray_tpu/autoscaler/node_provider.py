"""Node providers: the autoscaler's cloud abstraction.

Reference analog: ``python/ray/autoscaler/node_provider.py`` (NodeProvider
ABC) + v2's instance manager cloud interface. ``LocalNodeProvider`` spawns
worker-node processes on this machine — the test/single-host provider the
reference implements as ``autoscaler/_private/fake_multi_node``; a GKE/TPU
provider implements the same three methods with cloud instance calls.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class NodeProvider(ABC):
    @abstractmethod
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        """Launch one node; returns a provider node id."""

    @abstractmethod
    def terminate_node(self, provider_node_id: str):
        ...

    @abstractmethod
    def non_terminated_nodes(self) -> List[dict]:
        """[{provider_node_id, node_type, node_id (cluster id, may be None)}]"""


class LocalNodeProvider(NodeProvider):
    """Spawns worker_main processes against a head address."""

    def __init__(self, head_address: str):
        host, _, port = head_address.rpartition(":")
        self._gcs_addr = (host or "127.0.0.1", int(port))
        self._nodes: Dict[str, dict] = {}
        self._counter = 0

    def create_node(self, node_type, resources, labels=None) -> str:
        from ray_tpu._private.ids import JobID
        from ray_tpu._private.node import spawn_node

        handle = spawn_node(
            self._gcs_addr, JobID.from_random(), dict(resources), labels
        )
        pid = f"local-{self._counter}"
        self._counter += 1
        self._nodes[pid] = {
            "provider_node_id": pid,
            "node_type": node_type,
            "node_id": handle.node_id,
            "handle": handle,
        }
        return pid

    def terminate_node(self, provider_node_id: str):
        info = self._nodes.pop(provider_node_id, None)
        if info is not None:
            info["handle"].terminate()

    def non_terminated_nodes(self) -> List[dict]:
        out = []
        for pid, info in list(self._nodes.items()):
            if info["handle"].alive():
                out.append({k: info[k] for k in
                            ("provider_node_id", "node_type", "node_id")})
            else:
                self._nodes.pop(pid, None)
        return out
