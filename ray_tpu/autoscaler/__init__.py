from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    AutoscalerMonitor,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.node_provider import (
    GCETPUNodeProvider,
    KubernetesNodeProvider,
    LocalNodeProvider,
    NodeProvider,
)

__all__ = [
    "Autoscaler", "AutoscalerConfig", "AutoscalerMonitor", "NodeTypeConfig",
    "NodeProvider", "LocalNodeProvider", "GCETPUNodeProvider",
    "KubernetesNodeProvider",
]
