"""User-facing exceptions (reference: ``python/ray/exceptions.py``)."""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Re-raised at ``get()`` on the caller, wrapping the remote traceback
    (reference: ``RayTaskError``).
    """

    def __init__(self, cause_repr: str, traceback_str: str = "", cause=None):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Task failed: {cause_repr}\n{traceback_str}")


class ActorError(RayTpuError):
    """The actor died before or during this method call (reference: RayActorError)."""


class ActorDiedError(ActorError):
    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} died: {reason}")


class ActorUnavailableError(ActorError):
    """Actor temporarily unreachable; the call may be retried."""


class ObjectLostError(RayTpuError):
    """Object could not be found or reconstructed (reference: ObjectLostError)."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost: {reason}")


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store is out of capacity."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(..., timeout=)`` expired before the object was ready."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class NodeDiedError(RayTpuError):
    """A node was marked dead by the head's health checker."""


class OutOfMemoryError(WorkerCrashedError):
    """A node rejected/killed the task under memory pressure (reference:
    memory-monitor-driven worker killing; subclasses WorkerCrashedError so
    the submitter's retry path treats it as retriable)."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to materialize the runtime environment for a task/actor."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement group cannot be scheduled (e.g. infeasible slice topology)."""


class PendingCallsLimitExceededError(RayTpuError):
    """Actor's max_pending_calls budget exhausted (backpressure signal)."""


class LintError(RayTpuError):
    """Static-analysis check failed at ``@remote`` decoration time.

    Raised when ``RAY_TPU_LINT=1`` and ``ray_tpu.lint`` finds a
    distributed-correctness hazard (non-picklable closure capture,
    blocking get() in a task, unplaceable resources, ...) in the
    decorated function/class — before the bad task ever ships.
    ``findings`` holds the :class:`ray_tpu.lint.Finding` objects.
    """

    def __init__(self, findings):
        self.findings = list(findings)
        lines = [f.format() if hasattr(f, "format") else str(f)
                 for f in self.findings]
        super().__init__(
            "lint failed (%d finding%s):\n%s\nSuppress a line with "
            "'# raytpu: ignore[RULE]' or unset RAY_TPU_LINT."
            % (len(lines), "s" if len(lines) != 1 else "", "\n".join(lines))
        )
