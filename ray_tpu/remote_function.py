"""@remote functions (reference: ``python/ray/remote_function.py``)."""
from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional

from ray_tpu._private.worker import get_global_worker

# Option names accepted by .options() / @remote(**...), mirroring the
# reference's option surface (``python/ray/_private/ray_option_utils.py``)
# where it is meaningful on a TPU cluster.
_TASK_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "num_gpus",
    "resources",
    "num_returns",
    "max_retries",
    "name",
    "scheduling_strategy",
    "runtime_env",
    "label_selector",
}


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        resources["CPU"] = float(opts["num_cpus"])
    resources.setdefault("CPU", 1.0)
    if opts.get("num_tpus"):
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        resources["GPU"] = float(opts["num_gpus"])
    # zero-cpu tasks still need a slot marker so leases terminate
    if resources.get("CPU") == 0:
        resources.pop("CPU")
        resources.setdefault("node:slot", 0.001)
    return resources


def _build_strategy(opts: Dict[str, Any]) -> dict:
    strategy: dict = {}
    ss = opts.get("scheduling_strategy")
    if ss is not None:
        if isinstance(ss, str):
            if ss == "SPREAD":
                strategy["spread"] = True
        else:  # strategy object from util.scheduling_strategies
            strategy.update(ss.to_dict())
    if opts.get("label_selector"):
        strategy["labels"] = dict(opts["label_selector"])
    return strategy


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        functools.update_wrapper(self, fn)
        # Opt-in decoration-time static analysis: raise LintError on
        # distributed-correctness hazards before the task ever ships.
        # Runs again on .options() copies so dynamically merged resource
        # shapes are validated too. The truthy env probe keeps the lint
        # import lazy; lint_enabled() is the authoritative gate.
        if os.environ.get("RAY_TPU_LINT"):
            from ray_tpu.lint import check_remote_function, lint_enabled

            if lint_enabled():
                check_remote_function(fn, self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._fn.__name__}' cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )

    def options(self, **opts) -> "RemoteFunction":
        bad = set(opts) - _TASK_OPTIONS
        if bad:
            raise ValueError(f"unknown task options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(opts)
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        refs = worker.submit_task(
            self._fn,
            args,
            kwargs,
            num_returns=num_returns,
            resources=_build_resources(opts),
            strategy=_build_strategy(opts),
            max_retries=opts.get("max_retries", 3),
            name=opts.get("name", ""),
            runtime_env=opts.get("runtime_env"),
        )
        if num_returns == "streaming":
            return refs  # a StreamingObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs

    @property
    def underlying_function(self):
        return self._fn
