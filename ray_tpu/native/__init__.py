"""Native (C++) runtime components, loaded via ctypes.

The shared library is built from ``src/`` on first import (g++ is part of the
toolchain; there is no server process to deploy — the arena lives in shm and
every process coordinates through its header). If the toolchain is missing or
the build fails, importers fall back to the portable Python implementations.
"""
from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import threading

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "librt_native.so")
_SRC = os.path.join(_DIR, "src", "arena_store.cc")

_lock = threading.Lock()
_lib = None
_tried = False

# make_target -> compiler stderr for builds that FAILED with a working
# toolchain. A compile error is a bug in this repo, not an environment
# limitation — tests must fail (not skip) and bench must label fallback runs.
_build_errors: dict = {}


def toolchain_available() -> bool:
    return shutil.which("g++") is not None and shutil.which("make") is not None


def build_failure(target: str = None):
    """Compiler output for native targets that failed to COMPILE with the
    toolchain present, or None. Distinct from toolchain_available() so callers
    can tell "can't build here" from "the code is broken". Pass a make target
    (e.g. "librt_native.so") to scope the check to one library."""
    if target is not None:
        return _build_errors.get(target)
    if not _build_errors:
        return None
    return "\n".join(
        "%s:\n%s" % (t, err) for t, err in _build_errors.items()
    )


def _lib_needs_build(lib_path: str, srcs) -> bool:
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    return any(
        os.path.exists(s) and os.path.getmtime(s) > lib_mtime for s in srcs
    )


def build_lib(make_target: str, lib_path: str, srcs) -> bool:
    """Build one native library under an exclusive file lock: N workers can
    start concurrently and must not relink the .so while another process
    dlopens it (the link itself is also atomic — temp output + rename, see
    Makefile). Shared by every native component's loader."""
    import fcntl

    try:
        with open(os.path.join(_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            if not _lib_needs_build(lib_path, srcs):
                return True  # another process built while we waited
            res = subprocess.run(
                ["make", "-C", _DIR, make_target],
                capture_output=True,
                text=True,
                timeout=120,
            )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build (%s) unavailable: %s", make_target, e)
        return False
    if res.returncode != 0:
        if toolchain_available():
            _build_errors[make_target] = res.stderr[-2000:]
            logger.error(
                "native build (%s) FAILED with the toolchain present — this "
                "is a compile error in the repo, not a missing toolchain:\n%s",
                make_target,
                res.stderr[-2000:],
            )
        else:
            logger.warning(
                "native build (%s) failed:\n%s", make_target, res.stderr[-2000:]
            )
        return False
    return True


def build_and_load(make_target: str, lib_path: str, srcs):
    """Build (if stale) and dlopen one native library; None on failure.
    Callers cache the handle and set up their own argtypes."""
    if _lib_needs_build(lib_path, srcs):
        if not build_lib(make_target, lib_path, srcs):
            return None
    try:
        return ctypes.CDLL(lib_path)
    except OSError as e:
        logger.warning("native load of %s failed: %s", lib_path, e)
        return None


def _needs_build() -> bool:
    return _lib_needs_build(_LIB_PATH, [_SRC])


def _build() -> bool:
    return build_lib("librt_native.so", _LIB_PATH, [_SRC])


def load_library():
    """Return the ctypes lib, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _needs_build():
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("native library load failed: %s", e)
            return None
        try:
            _bind_symbols(lib)
        except AttributeError as e:
            # A stale/mismatched .so (symbol missing) must degrade to the
            # fallback store, not crash worker startup.
            logger.error("native library symbol mismatch: %s", e)
            return None
        _lib = lib
        return _lib


def _bind_symbols(lib) -> None:
    lib.rt_arena_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
    ]
    lib.rt_arena_create.restype = ctypes.c_int
    lib.rt_arena_attach.argtypes = [ctypes.c_char_p]
    lib.rt_arena_attach.restype = ctypes.c_int
    lib.rt_arena_unlink.argtypes = [ctypes.c_char_p]
    lib.rt_arena_unlink.restype = ctypes.c_int
    lib.rt_arena_detach.argtypes = [ctypes.c_int]
    lib.rt_arena_detach.restype = ctypes.c_int
    lib.rt_arena_base.argtypes = [ctypes.c_int]
    lib.rt_arena_base.restype = ctypes.c_void_p
    lib.rt_arena_capacity.argtypes = [ctypes.c_int]
    lib.rt_arena_capacity.restype = ctypes.c_uint64
    lib.rt_obj_create.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.rt_obj_create.restype = ctypes.c_int64
    lib.rt_obj_seal.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_obj_seal.restype = ctypes.c_int
    lib.rt_obj_get.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_obj_get.restype = ctypes.c_int64
    lib.rt_obj_release.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_obj_release.restype = ctypes.c_int
    lib.rt_obj_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_obj_delete.restype = ctypes.c_int
    lib.rt_obj_contains.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_obj_contains.restype = ctypes.c_int
    lib.rt_arena_stats.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_arena_stats.restype = None
    lib.rt_test_hold_lock.argtypes = [ctypes.c_int]
    lib.rt_test_hold_lock.restype = ctypes.c_int
    lib.rt_arena_num_tombs.argtypes = [ctypes.c_int]
    lib.rt_arena_num_tombs.restype = ctypes.c_uint64
    lib.rt_arena_scrub.argtypes = [ctypes.c_int]
    lib.rt_arena_scrub.restype = ctypes.c_int
    lib.rt_memcpy_parallel.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.rt_memcpy_parallel.restype = None
    lib.rt_arena_copy.argtypes = [
        ctypes.c_int, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.rt_arena_copy.restype = ctypes.c_int
