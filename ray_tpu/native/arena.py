"""Python client for the native shm arena store (plasma-analog client).

Exposes the same interface as ``_private/object_store.LocalShmStore`` so the
worker can swap backends: ``put_frames``/``get_frames``/``contains``/``free``/
``close_all``. Objects are stored with the identical frame layout
([u32 nframes][u64 len]*n, 8-aligned payloads) so serialization code sees no
difference; the payload just lives in one node-wide arena instead of one shm
segment per object.

Semantics mirrored from the reference store
(src/ray/object_manager/plasma/store.cc): create→write→seal by the producer,
get pins, delete defers reclamation until the last pin drops. The Python side
tracks this process's pins and its created objects so ``free`` maps onto
release (reader) or delete (owner).
"""
from __future__ import annotations

import ctypes
import logging
import os
import struct
import time
import weakref
from typing import List, Optional

from ray_tpu import native as _native
from ray_tpu._private.backoff import Backoff as _Backoff
from ray_tpu._private.object_store import LocalShmStore

logger = logging.getLogger(__name__)

_ALIGN = 8
_HDR_COUNT = struct.Struct("<I")
_HDR_LEN = struct.Struct("<Q")

# 4 GiB virtual default: pages are faulted on demand by the native
# prefault watermark, so an idle session costs ~nothing — while put-heavy
# multi-client workloads stop spilling into cold per-object fallback
# segments (the round-2 multi_client_put collapse). _shm_budget still caps
# this below what /dev/shm can actually hold.
DEFAULT_CAPACITY = int(os.environ.get("RT_ARENA_BYTES", 4 << 30))
INDEX_SLOTS = 1 << 15


# Frames at/above this size take the native copy path (GIL released; NT
# streaming stores from 16MB by auto-probe — RT_STREAM_MIN_MB overrides —
# and one extra copy thread per 4MB up to the coordinated budget).
_PARALLEL_COPY_MIN = 1024 * 1024


def _buffer_address(b) -> Optional[int]:
    """Stable address of a bytes/writable-buffer payload for the duration of
    the copy (the caller keeps ``b`` alive); None when not obtainable
    zero-copy (e.g. a read-only non-bytes view)."""
    if isinstance(b, bytes):
        return ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p).value
    try:
        mv = memoryview(b)
        if not mv.c_contiguous:
            return None
        if mv.readonly:
            return None
        arr = (ctypes.c_char * mv.nbytes).from_buffer(mv)
        return ctypes.addressof(arr)
    except (TypeError, ValueError):
        return None


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _shm_budget(requested: int) -> int:
    """Cap the arena below what /dev/shm can actually hold."""
    try:
        st = os.statvfs("/dev/shm")
        free = st.f_bavail * st.f_frsize
        return max(min(requested, int(free * 0.4)), 1 << 24)
    except OSError:
        return requested


class NativeArenaStore:
    """ctypes client for one named arena. Raises RuntimeError if the native
    library is unavailable or the arena cannot be created/attached."""

    def __init__(self, name: str, capacity: Optional[int] = None,
                 create: bool = True, index_slots: int = INDEX_SLOTS):
        lib = _native.load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if capacity is None:
            # resolved at call time so tests/env can size a fresh session's
            # arena without re-importing the module
            from ray_tpu._private.config import rt_config

            capacity = rt_config.arena_bytes
        self._lib = lib
        self.name = name
        self.created_arena = False
        h = lib.rt_arena_attach(name.encode())
        if h < 0 and create:
            cap = _shm_budget(capacity)
            h = lib.rt_arena_create(name.encode(), cap, index_slots)
            if h >= 0:
                self.created_arena = True
            elif h == -17:  # EEXIST: lost the creation race
                h = lib.rt_arena_attach(name.encode())
        # The creator publishes the header magic last; an attach landing in
        # its init window (file exists, magic unset → EPROTO/EINVAL) must
        # wait it out, not fall back for the process's whole lifetime.
        deadline = time.monotonic() + 5.0
        attach_poll = _Backoff(base=0.01, cap=0.1)
        while h < 0 and h != -2 and time.monotonic() < deadline:  # -2=ENOENT
            attach_poll.sleep()
            h = lib.rt_arena_attach(name.encode())
        if h < 0:
            raise RuntimeError(f"arena {name}: errno {-h}")
        self._h = h
        self._base = lib.rt_arena_base(h)
        # Objects this process created (free() maps to delete for these).
        # Reader pins are owned by the buffers themselves: get_frames attaches
        # a finalizer to the mapping window so the pin drops only when the
        # last zero-copy view dies (plasma client-buffer semantics).
        # Insertion-ordered (dict): creation order doubles as the
        # spill-eviction order (oldest first).
        self._created: dict = {}

    # -- store interface ----------------------------------------------------

    def put_frames(self, object_hex: str, frames: List[bytes]) -> Optional[dict]:
        """Returns meta, or None when the arena is full (caller falls back)."""
        total = _HDR_COUNT.size + _HDR_LEN.size * len(frames)
        offsets = []
        for f in frames:
            total = _align(total)
            offsets.append(total)
            total += len(f)
        off = self._lib.rt_obj_create(self._h, object_hex.encode(), max(total, 1))
        if off < 0:
            if off in (-28, -23):  # ENOSPC / ENFILE
                return None
            raise RuntimeError(f"obj_create({object_hex}): errno {-off}")
        buf = self._view(off, total)
        _HDR_COUNT.pack_into(buf, 0, len(frames))
        pos = _HDR_COUNT.size
        for f in frames:
            _HDR_LEN.pack_into(buf, pos, len(f))
            pos += _HDR_LEN.size
        for o, f in zip(offsets, frames):
            n = len(f)
            if n >= _PARALLEL_COPY_MIN:
                src = _buffer_address(f)
                if src is not None:
                    # native streaming copy, thread budget shared across
                    # every process putting into this arena concurrently
                    rc = self._lib.rt_arena_copy(self._h, off + o, src, n)
                    if rc != 0:
                        # Never seal an unwritten payload (readers would get
                        # garbage) — and delete the created entry so the id
                        # isn't wedged in kCreated holding its allocation.
                        self._lib.rt_obj_delete(self._h, object_hex.encode())
                        raise RuntimeError(
                            f"arena_copy({object_hex}): errno {-rc}"
                        )
                    continue
            buf[o : o + n] = f
        rc = self._lib.rt_obj_seal(self._h, object_hex.encode())
        if rc != 0:
            # Same leak class as a failed copy: never leave the id wedged
            # in kCreated holding its allocation.
            self._lib.rt_obj_delete(self._h, object_hex.encode())
            raise RuntimeError(f"obj_seal({object_hex}): errno {-rc}")
        # Value is the sealed size (truthy — callers only gate on presence):
        # per-process created-bytes accounting for the memtrack plane.
        self._created[object_hex] = total
        return {"arena": self.name, "size": total}

    def get_frames(self, object_hex: str, meta: dict) -> Optional[List[memoryview]]:
        size = ctypes.c_uint64()
        off = self._lib.rt_obj_get(self._h, object_hex.encode(), ctypes.byref(size))
        if off < 0:
            return None
        arr = (ctypes.c_char * size.value).from_address(self._base + off)
        # The pin taken by rt_obj_get is released when the last view into this
        # window is GC'd — deserialized arrays alias arena memory, so the
        # block must not be reused while any of them is alive. (Reference:
        # plasma client buffers release on destruction.) atexit=False: at
        # interpreter exit the arena is torn down wholesale anyway.
        fin = weakref.finalize(
            arr, self._lib.rt_obj_release, self._h, object_hex.encode()
        )
        fin.atexit = False
        buf = memoryview(arr).cast("B")
        nframes = _HDR_COUNT.unpack_from(buf, 0)[0]
        lens = []
        pos = _HDR_COUNT.size
        for _ in range(nframes):
            lens.append(_HDR_LEN.unpack_from(buf, pos)[0])
            pos += _HDR_LEN.size
        out = []
        for ln in lens:
            pos = _align(pos)
            out.append(buf[pos : pos + ln])
            pos += ln
        return out

    def contains(self, object_hex: str) -> bool:
        return bool(self._lib.rt_obj_contains(self._h, object_hex.encode()))

    def free(self, object_hex: str, meta: Optional[dict] = None):
        enc = object_hex.encode()
        if object_hex in self._created:
            self._created.pop(object_hex, None)
            self._lib.rt_obj_delete(self._h, enc)
        elif meta is not None:
            # Owner-side free of an object this process didn't create (e.g.
            # the creator died and the head reassigned ownership). Drops the
            # (possibly leaked) creator pin and marks the block deletable.
            self._lib.rt_obj_delete(self._h, enc)
        # Reader-side free (meta=None, not creator) is a no-op: get-pins are
        # released by the buffer finalizers when the views die.

    def close_all(self):
        for hex_ in list(self._created):
            self.free(hex_)
        if self.created_arena:
            self._lib.rt_arena_unlink(self.name.encode())

    # -- helpers ------------------------------------------------------------

    def _view(self, off: int, size: int) -> memoryview:
        arr = (ctypes.c_char * size).from_address(self._base + off)
        return memoryview(arr).cast("B")

    def created_stats(self) -> dict:
        """This process's contribution to the shared arena: objects it
        created (and still holds) with their sealed sizes."""
        n = b = 0
        for v in list(self._created.values()):
            n += 1
            b += int(v)
        return {"objects": n, "bytes": b}

    def created_oids(self) -> List[str]:
        return list(self._created)

    def stats(self) -> dict:
        used = ctypes.c_uint64()
        nobj = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        peak = ctypes.c_uint64()
        self._lib.rt_arena_stats(
            self._h, ctypes.byref(used), ctypes.byref(nobj),
            ctypes.byref(cap), ctypes.byref(peak),
        )
        return {
            "bytes_in_use": used.value,
            "num_objects": nobj.value,
            "capacity": cap.value,
            "peak_bytes": peak.value,
        }


class HybridShmStore:
    """Arena-first store with per-object-segment fallback.

    Mirrors plasma's fallback allocation (create_request_queue falling back to
    filesystem-backed mmap when the main arena is exhausted): puts go to the
    native arena; on arena-full (or no native toolchain) they land in a
    per-object POSIX shm segment via the portable store. Reads dispatch on the
    meta descriptor ("arena" vs "seg" key).
    """

    def __init__(self, arena_name: Optional[str], prefix: str = "rt"):
        self.fallback = LocalShmStore(prefix=prefix)
        self.arena: Optional[NativeArenaStore] = None
        # Disk spilling (reference: local_object_manager SpillObjects /
        # AsyncRestoreSpilledObject). spill_handler is installed by the
        # CoreWorker: called with the byte count needed, returns bytes it
        # freed from the arena by spilling sealed objects to disk.
        from ray_tpu._private.spill import SpillManager

        self.spill = SpillManager(session=(arena_name or "anon").strip("/"))
        self.spill_handler = None
        from ray_tpu._private.config import rt_config

        if arena_name and not rt_config.disable_native_store:
            try:
                self.arena = NativeArenaStore(arena_name)
            except (RuntimeError, OSError) as e:
                logger.debug("native arena unavailable (%s); portable store", e)

    @property
    def native_enabled(self) -> bool:
        return self.arena is not None

    def put_frames(self, object_hex: str, frames: List[bytes],
                   transient: bool = False) -> dict:
        if self.arena is not None:
            # arena blocks reclaim for real on delete: transient is only
            # meaningful for the per-segment fallback store
            meta = self.arena.put_frames(object_hex, frames)
            if meta is None and self.spill_handler is not None:
                # Arena full: spill cold sealed objects to disk, retry once.
                need = sum(len(f) for f in frames) + 4096
                try:
                    freed = self.spill_handler(need)
                except Exception:
                    logger.exception("spill handler failed")
                    freed = 0
                if freed > 0:
                    meta = self.arena.put_frames(object_hex, frames)
            if meta is not None:
                return meta
        return self.fallback.put_frames(object_hex, frames,
                                        transient=transient)

    def get_frames(self, object_hex: str, meta: dict) -> Optional[List[memoryview]]:
        if "spill" in meta:
            frames = self.spill.read(meta)
            return (
                [memoryview(f) for f in frames] if frames is not None else None
            )
        if "arena" in meta:
            if self.arena is None:
                return None
            return self.arena.get_frames(object_hex, meta)
        return self.fallback.get_frames(object_hex, meta)

    def contains(self, object_hex: str) -> bool:
        if self.arena is not None and self.arena.contains(object_hex):
            return True
        return self.fallback.contains(object_hex)

    def free(self, object_hex: str, meta: Optional[dict] = None):
        if meta is not None and "spill" in meta:
            self.spill.delete(meta)
            return
        if meta is not None and "seg" in meta:
            self.fallback.free(object_hex, meta)
            return
        if self.arena is not None:
            self.arena.free(object_hex, meta)
            # The owner's meta can be stale (a sibling process spilled the
            # object after the owner cached the arena meta): also drop any
            # spilled copy, or frees leak spill objects for the session's
            # life (key_uri: scheme-aware — file path or bucket uri).
            self.spill.delete({"spill": self.spill.key_uri(object_hex)})
        if meta is None:
            self.fallback.free(object_hex)

    def stats(self) -> dict:
        """Store-plane accounting for the memtrack gauges: node-wide arena
        counters (None without the native toolchain), this process's
        fallback-segment and graveyard bytes, and the spill counters."""
        from ray_tpu._private.object_store import graveyard_stats

        return {
            "arena": self.arena.stats() if self.arena is not None else None,
            "arena_created": (
                self.arena.created_stats() if self.arena is not None
                else {"objects": 0, "bytes": 0}
            ),
            "fallback": self.fallback.created_stats(),
            "graveyard": graveyard_stats(),
            "spill": self.spill.stats_snapshot(),
        }

    def created_oids(self) -> List[str]:
        """Objects this process created and still holds in either store —
        the 'a live mapping still backs this directory entry' signal the
        leak detector checks before flagging an orphan."""
        oids = self.fallback.created_oids()
        if self.arena is not None:
            oids += self.arena.created_oids()
        return oids

    def close_all(self):
        if self.arena is not None:
            if self.arena.created_arena:
                # Session teardown (we created the arena → we are the
                # session's first process): remove the spill directory too.
                self.spill.cleanup()
            self.arena.close_all()
        self.fallback.close_all()
