"""ctypes wrapper for the native object-transfer plane (src/xfer.cc).

TPU-era equivalent of the reference's object_manager push/pull data plane
(``src/ray/object_manager/object_manager.h:128``): every worker runs one
C++ TCP server thread that serves object payloads straight out of shm
(per-object segments or the arena), and remote workers fetch them into a
local segment without touching the Python RPC plane. Falls back silently —
callers keep the asyncio inline-pull path when the library is unavailable.
"""
from __future__ import annotations

import ctypes
import errno as _errno
import logging
import os
import threading
from typing import Optional

from ray_tpu.native import build_and_load

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "librt_xfer.so")
_SRCS = [
    os.path.join(_DIR, "src", "xfer.cc"),
    os.path.join(_DIR, "src", "arena_store.cc"),
]

_lock = threading.Lock()
_lib = None
_tried = False


def _load_library():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = build_and_load("librt_xfer.so", _LIB_PATH, _SRCS)
        if lib is None:
            return None
        lib.rt_xfer_serve.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.rt_xfer_serve.restype = ctypes.c_int
        lib.rt_xfer_stop.argtypes = [ctypes.c_int]
        lib.rt_xfer_stop.restype = ctypes.c_int
        lib.rt_xfer_fetch.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.rt_xfer_fetch.restype = ctypes.c_int64
        lib.rt_xfer_set_token.argtypes = [ctypes.c_char_p]
        lib.rt_xfer_set_token.restype = None
        _lib = lib
        return _lib


def _sync_token(lib):
    """Push the current cluster token into the native plane. Called before
    every serve/fetch: the Python side reads the env under the GIL and the
    C side stores it behind a mutex — no getenv from serving threads
    (racing Python's setenv/unsetenv is POSIX-undefined)."""
    import os

    lib.rt_xfer_set_token(os.environ.get("RT_AUTH_TOKEN", "").encode())


def start_server(host: str = "127.0.0.1") -> Optional[int]:
    """Start this process's transfer server; returns the bound port or
    None when the native library is unavailable. ``host`` should be the
    same address the worker's RPC plane advertises — the transfer plane
    must not be reachable more widely than the rest of the runtime."""
    lib = _load_library()
    if lib is None:
        return None
    _sync_token(lib)
    port = lib.rt_xfer_serve(host.encode(), 0)
    if port < 0:
        logger.warning("xfer server failed to start: errno %d", -port)
        return None
    return port


def stop_server(port: int) -> bool:
    """Stop a server started by :func:`start_server` (closes the listener;
    in-flight transfers drain on their own threads)."""
    lib = _load_library()
    if lib is None:
        return False
    return lib.rt_xfer_stop(int(port)) == 0


def fetch_to_segment(
    host: str, port: int, meta: dict, object_hex: str, dest_seg: str,
    timeout_s: Optional[float] = None,
) -> Optional[dict]:
    """Fetch a remote object into local segment ``dest_seg``. ``meta`` is
    the object's directory metadata ({"seg": ...} or {"arena": ...}).
    Returns per-segment metadata for the local store, or None on failure
    (caller falls back to the RPC pull). ``timeout_s`` bounds connect and
    every socket read/write."""
    lib = _load_library()
    if lib is None:
        return None
    _sync_token(lib)
    if "seg" in meta:
        kind, name1, name2 = 0, meta["seg"], ""
    elif "arena" in meta:
        kind, name1, name2 = 1, meta["arena"], object_hex
    else:
        return None
    # never 0: the C side treats <=0 as "no IO bound", which would invert a
    # nearly-expired deadline into unbounded blocking
    timeout_ms = max(1, int(timeout_s * 1000)) if timeout_s else 600_000
    n = lib.rt_xfer_fetch(
        host.encode(), int(port), kind,
        name1.encode(), name2.encode(), dest_seg.encode(), timeout_ms,
    )
    if n == -_errno.EEXIST:
        # A complete local copy already exists (publication is by atomic
        # rename, so existence implies completeness).
        return {"seg": dest_seg, "size": 0}
    if n < 0:
        logger.debug(
            "native fetch of %s from %s:%s failed: errno %d",
            object_hex[:8], host, port, -n,
        )
        return None
    return {"seg": dest_seg, "size": int(n)}
