"""ctypes wrapper for the native cluster resource scheduler (src/sched.cc).

TPU-era equivalent of the reference's C++ scheduling stack
(``src/ray/common/scheduling/`` + ``src/ray/raylet/scheduling/policy/``):
fixed-point resource accounting with interned resource ids and
hybrid/spread/affinity/label best-node selection, embedded in the head
service. ``create()`` returns a :class:`NativeScheduler` or ``None`` when the
native toolchain is unavailable (callers keep the Python fallback).
"""
from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Dict, Iterable, Optional

from ray_tpu.native import build_and_load

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "librt_sched.so")
_SRC = os.path.join(_DIR, "src", "sched.cc")

_lock = threading.Lock()
_lib = None
_tried = False


def _load_library():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = build_and_load("librt_sched.so", _LIB_PATH, [_SRC])
        if lib is None:
            return None

        c_char_pp = ctypes.POINTER(ctypes.c_char_p)
        c_double_p = ctypes.POINTER(ctypes.c_double)
        lib.rts_sched_new.argtypes = []
        lib.rts_sched_new.restype = ctypes.c_void_p
        lib.rts_sched_free.argtypes = [ctypes.c_void_p]
        lib.rts_sched_free.restype = None
        lib.rts_sched_add_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_sched_add_node.restype = ctypes.c_int
        lib.rts_sched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_sched_remove_node.restype = ctypes.c_int
        lib.rts_sched_set_alive.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.rts_sched_set_alive.restype = ctypes.c_int
        lib.rts_sched_set_resource.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_double,
        ]
        lib.rts_sched_set_resource.restype = ctypes.c_int
        lib.rts_sched_set_label.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.rts_sched_set_label.restype = ctypes.c_int
        for name in ("rts_sched_acquire", "rts_sched_release"):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, c_char_pp, c_double_p,
                ctypes.c_int,
            ]
            fn.restype = ctypes.c_int
        lib.rts_sched_fits.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, c_char_pp, c_double_p,
            ctypes.c_int,
        ]
        lib.rts_sched_fits.restype = ctypes.c_int
        lib.rts_sched_available.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.rts_sched_available.restype = ctypes.c_double
        lib.rts_sched_num_nodes.argtypes = [ctypes.c_void_p]
        lib.rts_sched_num_nodes.restype = ctypes.c_int
        lib.rts_sched_best_node.argtypes = [
            ctypes.c_void_p, c_char_pp, c_double_p, ctypes.c_int,  # demand
            ctypes.c_int,  # spread
            ctypes.c_char_p,  # affinity
            c_char_pp, c_char_pp, ctypes.c_int,  # labels
            c_char_pp, ctypes.c_int,  # avoid
            ctypes.c_char_p, ctypes.c_int,  # out
        ]
        lib.rts_sched_best_node.restype = ctypes.c_int
        _lib = lib
        return _lib


def _pack(need: Dict[str, float]):
    n = len(need)
    names = (ctypes.c_char_p * n)(*(k.encode() for k in need))
    vals = (ctypes.c_double * n)(*(float(v) for v in need.values()))
    return names, vals, n


class NativeScheduler:
    """Owns a native Sched instance; mirrors the head's resource tables."""

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.rts_sched_new()

    def __del__(self):
        h, self._h = self._h, None
        if h and self._lib:
            self._lib.rts_sched_free(h)

    def add_node(self, node_id: str, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None):
        nid = node_id.encode()
        self._lib.rts_sched_add_node(self._h, nid)
        for name, total in resources.items():
            self._lib.rts_sched_set_resource(
                self._h, nid, name.encode(), float(total)
            )
        for k, v in (labels or {}).items():
            self._lib.rts_sched_set_label(
                self._h, nid, k.encode(), str(v).encode()
            )

    def remove_node(self, node_id: str):
        self._lib.rts_sched_remove_node(self._h, node_id.encode())

    def set_alive(self, node_id: str, alive: bool):
        self._lib.rts_sched_set_alive(self._h, node_id.encode(), int(alive))

    def acquire(self, node_id: str, need: Dict[str, float]):
        names, vals, n = _pack(need)
        self._lib.rts_sched_acquire(self._h, node_id.encode(), names, vals, n)

    def release(self, node_id: str, need: Dict[str, float]):
        names, vals, n = _pack(need)
        self._lib.rts_sched_release(self._h, node_id.encode(), names, vals, n)

    def fits(self, node_id: str, need: Dict[str, float]) -> bool:
        names, vals, n = _pack(need)
        return bool(
            self._lib.rts_sched_fits(self._h, node_id.encode(), names, vals, n)
        )

    def available(self, node_id: str, resource: str) -> float:
        return self._lib.rts_sched_available(
            self._h, node_id.encode(), resource.encode()
        )

    def num_nodes(self) -> int:
        return self._lib.rts_sched_num_nodes(self._h)

    def best_node(
        self,
        need: Dict[str, float],
        *,
        spread: bool = False,
        affinity_node: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        avoid: Iterable[str] = (),
    ) -> Optional[str]:
        names, vals, n = _pack(need)
        labels = labels or {}
        nl = len(labels)
        lkeys = (ctypes.c_char_p * max(nl, 1))(
            *(k.encode() for k in labels) or (b"",)
        )
        lvals = (ctypes.c_char_p * max(nl, 1))(
            *(str(v).encode() for v in labels.values()) or (b"",)
        )
        avoid = list(avoid)
        na = len(avoid)
        av = (ctypes.c_char_p * max(na, 1))(
            *(a.encode() for a in avoid) or (b"",)
        )
        out = ctypes.create_string_buffer(256)
        found = self._lib.rts_sched_best_node(
            self._h, names, vals, n, int(spread),
            affinity_node.encode() if affinity_node else None,
            lkeys, lvals, nl, av, na, out, len(out),
        )
        return out.value.decode() if found else None


def create() -> Optional[NativeScheduler]:
    lib = _load_library()
    if lib is None:
        return None
    return NativeScheduler(lib)
