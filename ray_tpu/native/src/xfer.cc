// Native object-transfer plane: bulk object fetch between hosts.
//
// TPU-era equivalent of the reference's object_manager data plane
// (src/ray/object_manager/: ObjectManager object_manager.h:128, chunked
// PushManager/PullManager, ObjectBufferPool) — the path that moves object
// payloads BETWEEN machines. Intra-host sharing stays zero-copy through the
// shm arena / per-object segments; this server exposes those same bytes
// over TCP so a remote host's fetch never touches the Python RPC plane.
//
// Protocol (little-endian):
//   request:  u32 magic "RTX2" | u8 kind (0 = shm segment, 1 = arena object)
//             u16 len1, name1   (kind 0: segment name; kind 1: arena name)
//             u16 len2, name2   (kind 1: object hex; else empty)
//             u16 len3, token   (cluster auth token; empty = auth off)
//   response: u8 status (0 ok, 1 not found, 2 error) | u64 len | payload
//
// The payload is the segment's/object's raw bytes — the store's
// [u32 nframes][u64 len]*n | frames layout — so the fetching side writes it
// into a local segment verbatim and reads it with the normal store code.
//
// Server: one accept thread feeding a bounded fd queue drained by a fixed
// worker pool (2x cores, max 32) — bulk transfers keep the blocking write
// loop, but thread count and per-connection churn stay bounded at the
// many-node envelope. Arena attachments are cached per arena name.
// Serving pins arena objects via rt_obj_get/rt_obj_release; plain segments
// stay readable through the mmap even if unlinked mid-transfer.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

// Exported by arena_store.cc (linked into this same .so).
extern "C" {
int rt_arena_attach(const char* name);
void* rt_arena_base(int handle);
int64_t rt_obj_get(int handle, const char* object_hex, uint64_t* size_out);
int rt_obj_release(int handle, const char* object_hex);
}

namespace {

constexpr uint32_t kMagic = 0x32585452;  // "RTX2" (v2 adds the auth token)

// Cluster auth token (reference behavior: src/ray/rpc/authentication/
// token auth): cached from RT_AUTH_TOKEN at first use; the request's
// token field must match or the connection is dropped before any
// object bytes move. Empty env = auth disabled.
// Token storage: initialized from the env at library load (single
// threaded), updated through rt_xfer_set_token by the Python side on
// re-init/shutdown. NOT per-call getenv: serving threads racing a
// setenv/unsetenv from Python is POSIX-undefined (environ may be
// realloc'd mid-walk).
std::mutex g_token_mu;
std::string g_token = [] {
  const char* t = getenv("RT_AUTH_TOKEN");
  return std::string(t ? t : "");
}();

std::string expected_token() {
  std::lock_guard<std::mutex> lk(g_token_mu);
  return g_token;
}

// Only framework-owned shm names are served (segments "rt*", arenas "/rt*"):
// the server must not let a peer read arbitrary host shared memory.
bool AllowedName(const std::string& name) {
  size_t i = (!name.empty() && name[0] == '/') ? 1 : 0;
  return name.size() >= i + 2 && name[i] == 'r' && name[i + 1] == 't';
}

std::string ShmPath(const std::string& name) {
  std::string n = name;
  while (!n.empty() && n[0] == '/') n.erase(0, 1);
  return "/dev/shm/" + n;
}

void SetIoTimeout(int fd, int timeout_ms) {
  if (timeout_ms <= 0) return;
  struct timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

bool ReadFull(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= r;
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= r;
  }
  return true;
}

bool SendResponse(int fd, uint8_t status, const void* payload, uint64_t len) {
  if (!WriteFull(fd, &status, 1)) return false;
  if (!WriteFull(fd, &len, 8)) return false;
  if (len > 0 && !WriteFull(fd, payload, len)) return false;
  return true;
}

std::mutex g_arena_mu;
std::unordered_map<std::string, int> g_arenas;  // arena name -> handle

int ArenaHandle(const std::string& name) {
  std::lock_guard<std::mutex> lock(g_arena_mu);
  auto it = g_arenas.find(name);
  if (it != g_arenas.end()) return it->second;
  int h = rt_arena_attach(name.c_str());
  if (h >= 0) g_arenas.emplace(name, h);
  return h;
}

bool ReadName(int fd, std::string* out) {
  uint16_t len;
  if (!ReadFull(fd, &len, 2)) return false;
  if (len > 4096) return false;
  out->resize(len);
  return len == 0 || ReadFull(fd, out->data(), len);
}

void ServeSegment(int fd, const std::string& name) {
  std::string path = name;
  int sfd = shm_open(path.c_str(), O_RDONLY, 0);
  if (sfd < 0) {
    SendResponse(fd, 1, nullptr, 0);
    return;
  }
  struct stat st;
  if (fstat(sfd, &st) != 0 || st.st_size <= 0) {
    close(sfd);
    SendResponse(fd, 2, nullptr, 0);
    return;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, sfd, 0);
  close(sfd);
  if (base == MAP_FAILED) {
    SendResponse(fd, 2, nullptr, 0);
    return;
  }
  SendResponse(fd, 0, base, static_cast<uint64_t>(st.st_size));
  munmap(base, st.st_size);
}

void ServeArenaObject(int fd, const std::string& arena,
                      const std::string& hex) {
  int h = ArenaHandle(arena);
  if (h < 0) {
    SendResponse(fd, 1, nullptr, 0);
    return;
  }
  uint64_t size = 0;
  int64_t off = rt_obj_get(h, hex.c_str(), &size);
  if (off < 0) {
    SendResponse(fd, 1, nullptr, 0);
    return;
  }
  const char* base = static_cast<const char*>(rt_arena_base(h)) + off;
  SendResponse(fd, 0, base, size);
  rt_obj_release(h, hex.c_str());
}

void HandleConn(int fd) {
  uint32_t magic;
  uint8_t kind;
  std::string name1, name2;
  SetIoTimeout(fd, 120000);  // a wedged peer must not pin a thread forever
  std::string token;
  if (ReadFull(fd, &magic, 4) && magic == kMagic && ReadFull(fd, &kind, 1) &&
      ReadName(fd, &name1) && ReadName(fd, &name2) && ReadName(fd, &token)) {
    if (!expected_token().empty() && token != expected_token()) {
      // wrong/missing token: close without a response (an attacker learns
      // nothing about which objects exist)
    } else if (!AllowedName(name1)) {
      SendResponse(fd, 2, nullptr, 0);
    } else if (kind == 0) {
      ServeSegment(fd, name1);
    } else if (kind == 1) {
      ServeArenaObject(fd, name1, name2);
    } else {
      SendResponse(fd, 2, nullptr, 0);
    }
  }
  close(fd);
}

// Fixed worker pool draining a bounded fd queue. Transfers are bulk (the
// blocking write loop IS the right IO model for GB/s payloads); the pool
// bounds thread count and removes per-connection thread churn — a 250-node
// fetch storm costs queueing, not 250 thread spawns. Queue overflow sheds
// load by closing the connection: the fetcher falls back to the RPC pull
// path, which is the correct behavior under overload.
struct ServePool {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<int> fds;
  uint64_t epoch = 0;  // bumped on stop: workers of older epochs drain+exit
  unsigned workers = 0;
};

constexpr size_t kServeQueueMax = 256;

ServePool& pool() {
  // Intentionally leaked: pool workers block on the condvar, and a static
  // ServePool's destructor would run pthread_cond_destroy at process exit,
  // which blocks until all waiters wake — wedging interpreter shutdown for
  // any process that ever served a transfer. Detached workers die with the
  // process; the kernel reclaims the memory.
  static ServePool* p = new ServePool();
  return *p;
}

void PoolWorker(uint64_t my_epoch) {
  ServePool& p = pool();
  for (;;) {
    int fd;
    {
      std::unique_lock<std::mutex> lock(p.mu);
      p.cv.wait(lock, [&] {
        return p.epoch != my_epoch || !p.fds.empty();
      });
      if (p.fds.empty()) return;  // epoch advanced and nothing queued
      fd = p.fds.front();
      p.fds.pop_front();
    }
    HandleConn(fd);
  }
}

void EnsurePoolStarted() {
  ServePool& p = pool();
  std::lock_guard<std::mutex> lock(p.mu);
  if (p.workers > 0) return;
  unsigned hw = std::thread::hardware_concurrency();
  unsigned n = hw > 1 ? (hw * 2 < 32 ? hw * 2 : 32) : 2;
  for (unsigned i = 0; i < n; i++) {
    std::thread(PoolWorker, p.epoch).detach();
  }
  p.workers = n;
}

void StopPoolIfIdleListeners() {
  // Called with g_serve_mu held and g_listeners empty: quiesce the worker
  // pool (workers drain the queue, then exit); a later serve restarts it.
  ServePool& p = pool();
  {
    std::lock_guard<std::mutex> lock(p.mu);
    p.epoch++;
    p.workers = 0;
  }
  p.cv.notify_all();
}

void AcceptLoop(int listen_fd) {
  for (;;) {
    int fd = accept(listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      return;  // listen socket closed
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ServePool& p = pool();
    {
      std::lock_guard<std::mutex> lock(p.mu);
      if (p.fds.size() >= kServeQueueMax) {
        close(fd);  // shed load; fetcher falls back to the RPC pull
        continue;
      }
      p.fds.push_back(fd);
    }
    p.cv.notify_one();
  }
}

int Connect(const char* host, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (timeout_ms > 0) {
    // bounded connect: non-blocking + poll, then back to blocking IO with
    // SO_RCVTIMEO/SO_SNDTIMEO (get(timeout=...) must not hang on a wedged
    // owner host)
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
                     sizeof(addr));
    if (rc != 0 && errno == EINPROGRESS) {
      // poll, not select: long-lived workers can hold >FD_SETSIZE
      // descriptors, where FD_SET is a stack overflow
      struct pollfd pfd;
      pfd.fd = fd;
      pfd.events = POLLOUT;
      rc = poll(&pfd, 1, timeout_ms);
      if (rc <= 0) {
        close(fd);
        return rc == 0 ? -ETIMEDOUT : -errno;
      }
      int err = 0;
      socklen_t elen = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen);
      if (err != 0) {
        close(fd);
        return -err;
      }
    } else if (rc != 0) {
      int e = errno;
      close(fd);
      return -e;
    }
    fcntl(fd, F_SETFL, flags);
    SetIoTimeout(fd, timeout_ms);
  } else if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
                     sizeof(addr)) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

bool SendName(int fd, const std::string& s) {
  uint16_t len = static_cast<uint16_t>(s.size());
  return WriteFull(fd, &len, 2) && (len == 0 || WriteFull(fd, s.data(), len));
}

}  // namespace

namespace {
// Live listen sockets by bound port (for rt_xfer_stop).
std::mutex g_serve_mu;
std::unordered_map<int, int> g_listeners;  // port -> listen fd
}  // namespace

extern "C" {

// Start the transfer server on host:port (port 0 = ephemeral). Returns the
// bound port, or -errno. The accept thread runs until rt_xfer_stop.
int rt_xfer_serve(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  socklen_t alen = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&addr), &alen) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  int bound = ntohs(addr.sin_port);
  {
    std::lock_guard<std::mutex> lock(g_serve_mu);
    g_listeners[bound] = fd;
  }
  EnsurePoolStarted();
  std::thread(AcceptLoop, fd).detach();
  return bound;
}

// Stop a server started by rt_xfer_serve: closing the listen socket makes
// the accept loop exit (in-flight transfers finish on their own threads).
// A worker shutdown must not leave a listener serving this host's shm.
int rt_xfer_stop(int port) {
  int fd = -1;
  bool last = false;
  {
    std::lock_guard<std::mutex> lock(g_serve_mu);
    auto it = g_listeners.find(port);
    if (it == g_listeners.end()) return -ENOENT;
    fd = it->second;
    g_listeners.erase(it);
    last = g_listeners.empty();
  }
  shutdown(fd, SHUT_RDWR);
  close(fd);
  if (last) StopPoolIfIdleListeners();
  return 0;
}

// Fetch an object from a remote transfer server into local shm segment
// `dest_name`. kind 0: name1 = segment name; kind 1: name1 = arena name,
// name2 = object hex. The payload lands in a temp segment and is published
// to `dest_name` by atomic rename, so a segment under its final name is
// always complete — concurrent fetchers that find it existing may read it
// immediately. timeout_ms <= 0 means no IO bound. Returns the payload
// size, -EEXIST if a complete copy already exists locally, or -errno.
void rt_xfer_set_token(const char* token) {
  std::lock_guard<std::mutex> lk(g_token_mu);
  g_token = token ? token : "";
}

int64_t rt_xfer_fetch(const char* host, int port, int kind, const char* name1,
                      const char* name2, const char* dest_name,
                      int timeout_ms) {
  int pre = shm_open(dest_name, O_RDONLY, 0);
  if (pre >= 0) {
    close(pre);
    return -EEXIST;  // complete by the rename-publication invariant
  }
  int fd = Connect(host, port, timeout_ms);
  if (fd < 0) return fd;
  uint8_t k = static_cast<uint8_t>(kind);
  if (!WriteFull(fd, &kMagic, 4) || !WriteFull(fd, &k, 1) ||
      !SendName(fd, name1) || !SendName(fd, name2 ? name2 : "") ||
      !SendName(fd, expected_token())) {
    close(fd);
    return -EIO;
  }
  uint8_t status;
  uint64_t len;
  if (!ReadFull(fd, &status, 1) || !ReadFull(fd, &len, 8)) {
    close(fd);
    return -EIO;
  }
  if (status != 0) {
    close(fd);
    return status == 1 ? -ENOENT : -EIO;
  }
  // The temp name must be unique per *call*, not just per process: two
  // threads fetching the same object would collide on O_EXCL and the loser's
  // -EEXIST would be indistinguishable from "published copy exists" — it
  // would report completion while the segment is still mid-write.
  static std::atomic<uint64_t> fetch_seq{0};
  std::string tmp = std::string(dest_name) + ".t" +
                    std::to_string(getpid()) + "." +
                    std::to_string(fetch_seq.fetch_add(1));
  int dfd = shm_open(tmp.c_str(), O_RDWR | O_CREAT | O_EXCL, 0600);
  if (dfd < 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  int64_t result = -EIO;
  void* base = MAP_FAILED;
  if (ftruncate(dfd, static_cast<off_t>(len ? len : 1)) == 0) {
    base = mmap(nullptr, len ? len : 1, PROT_WRITE, MAP_SHARED, dfd, 0);
  }
  close(dfd);
  if (base != MAP_FAILED) {
    bool ok = len == 0 || ReadFull(fd, base, len);
    munmap(base, len ? len : 1);
    if (ok) {
      // Atomic publication (POSIX shm lives in /dev/shm on Linux): readers
      // can never observe a half-written segment under the final name.
      if (rename(ShmPath(tmp).c_str(), ShmPath(dest_name).c_str()) == 0) {
        result = static_cast<int64_t>(len);
      } else {
        result = -errno;
      }
    }
  }
  close(fd);
  if (result < 0) shm_unlink(tmp.c_str());
  return result;
}

}  // extern "C"
