// Native shared-memory arena object store (plasma equivalent).
//
// Reference behavior being reproduced (not copied):
//   src/ray/object_manager/plasma/{store.cc,object_store.cc,malloc.cc} — a
//   node-local shared-memory arena in which every large object lives exactly
//   once, written by its creator, sealed, then mapped zero-copy by readers,
//   with pin/release lifetime and delete deferred until the last pin drops.
//
// TPU-era design differences: no store server process. The arena is a single
// /dev/shm file; every process maps it MAP_SHARED and coordinates through a
// process-shared robust mutex in the arena header. All state lives at stable
// offsets (never raw pointers) so maps can land anywhere. The allocator is a
// boundary-tag explicit free list (first fit, split, coalesce) — plasma uses
// dlmalloc; we need only the create/free pattern of whole objects, where a
// simple coalescing allocator is equally effective and auditable.
//
// Concurrency: one mutex for index + heap. Object payload writes happen
// OUTSIDE the lock (the creator owns the block until seal; readers cannot see
// it before the sealed flag is set under the lock). Robustness: if a process
// dies holding the lock, the next locker gets EOWNERDEAD and marks the state
// consistent — index/heap invariants hold because all mutations are applied
// in crash-safe order (allocate fully, then publish).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#if defined(__x86_64__) || defined(__i386__)
#include <emmintrin.h>
#endif

#include <atomic>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kMagic = 0x52545F4152454E41ull;  // "RT_ARENA"
constexpr uint32_t kVersion = 5;  // v5: Entry tracks creator client + pin state
constexpr uint64_t kAlign = 16;
constexpr uint64_t kMinBlock = 48;  // hdr(8)+links(16)+ftr(8), padded to 16
constexpr uint32_t kIdBytes = 28;   // 56 hex chars

inline uint64_t align_up(uint64_t n, uint64_t a) { return (n + a - 1) & ~(a - 1); }

struct Entry {
  uint8_t id[kIdBytes];
  uint8_t state;  // 0 empty, 1 created, 2 sealed, 3 tombstone
  uint8_t deletable;
  // The creator's pin (pins starts at 1) may be dropped by ANY client's
  // rt_obj_delete — the owner of an object is often a different process
  // than its creator (task returns: worker creates, driver owns). Both the
  // owner's free AND the creator's own free (object_free pubsub fanout)
  // call delete; without this flag the second call would steal a READER's
  // pin and let the block be reclaimed under a live zero-copy view.
  uint8_t creator_client;    // ClientSlot index of the creator (0xFF none)
  uint8_t creator_unpinned;  // creator pin already dropped
  uint32_t pins;
  uint64_t off;   // payload offset in arena
  uint64_t size;  // payload size requested by the creator
  uint64_t seq;   // create sequence, for LRU-ish introspection
};
static_assert(sizeof(Entry) == 64, "Entry must be 64 bytes");

enum EntryState : uint8_t { kEmpty = 0, kCreated = 1, kSealed = 2, kTomb = 3 };

// Per-process pin accounting. Every process that maps the arena claims a
// ClientSlot; every pin it takes (creator pin at create, reader pin at get)
// is mirrored into its pin ledger. If the process dies without releasing
// (SIGKILL, actor kill at scale-down), a scrub detects the dead pid and
// subtracts its ledger from the entries — the serverless stand-in for
// plasma's client-disconnect cleanup (reference: plasma store releases all
// of a client's objects when its socket closes).
constexpr uint32_t kMaxClients = 32;

struct ClientSlot {
  uint32_t state;  // 0 free, 1 live
  uint32_t pid;
  uint64_t starttime;  // /proc/<pid>/stat field 22 (guards pid reuse)
};
static_assert(sizeof(ClientSlot) == 16, "ClientSlot must be 16 bytes");

struct PinRec {
  uint8_t id[kIdBytes];
  uint32_t count;  // 0 + zero id = empty; 0 + id = tombstone
};
static_assert(sizeof(PinRec) == 32, "PinRec must be 32 bytes");

struct ArenaHeader {
  uint64_t magic;
  uint32_t version;
  uint32_t index_slots;
  uint64_t capacity;
  uint64_t index_off;     // two index regions live here, back to back
  uint32_t active_index;  // 0/1: which region is live (flipped atomically)
  uint32_t _pad0;
  uint64_t heap_off;
  uint64_t heap_end;
  uint64_t free_head;  // offset of first free block header, 0 = none
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t peak_bytes;
  uint64_t create_seq;
  uint64_t num_evictions;
  uint64_t num_tombs;
  uint64_t epilogue_off;  // position of the size-0 terminator tag
  uint64_t client_off;    // ClientSlot[kMaxClients] then the pin ledgers
  uint32_t pin_slots;     // ledger slots per client (power of two)
  // Processes currently inside a payload copy (atomic). Concurrent putters
  // divide the copy-thread budget by this count so N clients don't spawn
  // N*8 threads and thrash (the cause of multi-client put throughput
  // dropping BELOW single-client). Same offset/size as the old _pad1, so
  // the layout (and kVersion) is unchanged.
  uint32_t active_copiers;
  pthread_mutex_t mutex;
  // Heap bytes already faulted in (atomic watermark). Cold tmpfs pages
  // fault at ~0.1 GB/s (vs multi-GB/s warm) and concurrent clients
  // contend on the kernel's page allocation — a background populate
  // thread keeps this ahead of the allocation frontier so payload copies
  // land on warm pages. Grows monotonically to heap_end.
  uint64_t populated_to;
};

struct Arena {
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  char name[256] = {0};
  bool used = false;
  int client = -1;  // this process's ClientSlot for this arena
  // Bumped on every claim of this slot: a detached populate thread holding
  // a stale generation must not touch a NEW arena that reused the slot.
  uint64_t gen = 0;
};

constexpr int kMaxArenas = 1024;
Arena g_arenas[kMaxArenas];
std::mutex g_table_mutex;  // protects the process-local arena table

int table_claim_slot() {
  for (int i = 0; i < kMaxArenas; i++) {
    if (!g_arenas[i].used) {
      g_arenas[i].used = true;
      g_arenas[i].gen += 1;
      return i;
    }
  }
  return -1;
}

bool handle_ok(int h) {
  return h >= 0 && h < kMaxArenas && g_arenas[h].used;
}

// Pins taken by the populate thread so it can fault pages without holding
// g_table_mutex; rt_arena_detach waits for the count to drain before munmap.
std::atomic<uint32_t> g_arena_pin[kMaxArenas];

// Ask for transparent huge pages on the heap region (tmpfs honors this when
// /sys/kernel/mm/transparent_hugepage/shmem_enabled is `advise`/`always`):
// 512x fewer first-touch faults and TLB entries for large-object traffic.
// Best-effort — EINVAL on kernels without shmem THP is fine.
void advise_hugepages(void* base, uint64_t heap_off, uint64_t heap_end) {
#ifdef MADV_HUGEPAGE
  uint64_t lo = (heap_off + (2ull << 20) - 1) & ~((2ull << 20) - 1);
  if (heap_end > lo) {
    madvise(static_cast<uint8_t*>(base) + lo, heap_end - lo, MADV_HUGEPAGE);
  }
#else
  (void)base; (void)heap_off; (void)heap_end;
#endif
}

inline ArenaHeader* hdr(Arena& a) { return reinterpret_cast<ArenaHeader*>(a.base); }
inline uint64_t index_region_bytes(ArenaHeader* h) {
  return (uint64_t)h->index_slots * sizeof(Entry);
}
inline Entry* index_of(Arena& a) {
  ArenaHeader* h = hdr(a);
  return reinterpret_cast<Entry*>(
      a.base + h->index_off + (h->active_index ? index_region_bytes(h) : 0));
}
inline Entry* index_inactive(Arena& a) {
  ArenaHeader* h = hdr(a);
  return reinterpret_cast<Entry*>(
      a.base + h->index_off + (h->active_index ? 0 : index_region_bytes(h)));
}

inline ClientSlot* clients_of(Arena& a) {
  return reinterpret_cast<ClientSlot*>(a.base + hdr(a)->client_off);
}
inline PinRec* pin_ledger(Arena& a, uint32_t client) {
  ArenaHeader* h = hdr(a);
  uint64_t base = h->client_off + kMaxClients * sizeof(ClientSlot);
  return reinterpret_cast<PinRec*>(
      a.base + base + (uint64_t)client * h->pin_slots * sizeof(PinRec));
}

// starttime from /proc/<pid>/stat (field 22, counted after the comm field,
// which may itself contain spaces/parens — parse from the last ')').
uint64_t read_starttime(uint32_t pid) {
  char path[64];
  snprintf(path, sizeof(path), "/proc/%u/stat", pid);
  FILE* f = fopen(path, "r");
  if (!f) return 0;
  char buf[1024];
  size_t n = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  buf[n] = 0;
  char* p = strrchr(buf, ')');
  if (!p) return 0;
  p++;
  // after ')': state is field 3; starttime is field 22 → 20th token
  uint64_t val = 0;
  for (int field = 3; field <= 22; field++) {
    while (*p == ' ') p++;
    if (field == 22) {
      val = strtoull(p, nullptr, 10);
      break;
    }
    while (*p && *p != ' ') p++;
  }
  return val;
}

bool process_alive(uint32_t pid, uint64_t starttime) {
  if (kill((pid_t)pid, 0) != 0 && errno == ESRCH) return false;
  if (starttime != 0) {
    uint64_t now = read_starttime(pid);
    if (now != 0 && now != starttime) return false;  // pid was reused
  }
  return true;
}

uint64_t fnv1a(const uint8_t* p, size_t n);  // fwd decl (defined below)

// Ledger add/sub for one id. delta=+1 inserts or increments; delta=-1
// decrements and tombstone-clears (with backward empty-conversion when the
// probe successor is empty, bounding tombstone buildup).
void pin_log_add(Arena& a, int client, const uint8_t* id, int delta) {
  if (client < 0) return;
  ArenaHeader* h = hdr(a);
  PinRec* tab = pin_ledger(a, (uint32_t)client);
  uint32_t slots = h->pin_slots;
  uint64_t start = fnv1a(id, kIdBytes) & (slots - 1);
  int64_t first_tomb = -1;
  for (uint32_t i = 0; i < slots; i++) {
    uint32_t sidx = (start + i) & (slots - 1);
    PinRec& r = tab[sidx];
    bool id_zero = r.id[0] == 0 && memcmp(r.id, r.id + 1, kIdBytes - 1) == 0;
    if (r.count == 0 && id_zero) {
      // empty terminator
      if (delta > 0) {
        uint32_t use = first_tomb >= 0 ? (uint32_t)first_tomb : sidx;
        memcpy(tab[use].id, id, kIdBytes);
        tab[use].count = (uint32_t)delta;
      }
      return;
    }
    if (memcmp(r.id, id, kIdBytes) == 0) {
      if (delta > 0) {
        r.count += (uint32_t)delta;
      } else if (r.count > 0) {
        r.count -= 1;
        if (r.count == 0) {
          // tombstone; convert to empty if the successor is empty
          uint32_t nxt = (sidx + 1) & (slots - 1);
          PinRec& rn = tab[nxt];
          bool nxt_empty = rn.count == 0 && rn.id[0] == 0 &&
                           memcmp(rn.id, rn.id + 1, kIdBytes - 1) == 0;
          if (nxt_empty) memset(r.id, 0, kIdBytes);
        }
      }
      return;
    }
    if (r.count == 0 && first_tomb < 0) first_tomb = sidx;
  }
  // ledger full: pin goes unlogged (unscrubbable but functionally correct)
}

// ------------------------------- heap ---------------------------------------
// Block: [u64 tag][payload...][u64 tag]; tag = size | alloc_bit. Free blocks
// keep {next,prev} free-list offsets at payload start. Heap is bracketed by an
// allocated prologue block and a size-0 allocated epilogue tag so coalescing
// never walks out of bounds.

inline uint64_t& tag_at(Arena& a, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(a.base + off);
}
inline uint64_t blk_size(Arena& a, uint64_t b) { return tag_at(a, b) & ~1ull; }
inline bool blk_alloc(Arena& a, uint64_t b) { return tag_at(a, b) & 1ull; }
inline void set_tags(Arena& a, uint64_t b, uint64_t size, bool alloc) {
  tag_at(a, b) = size | (alloc ? 1 : 0);
  tag_at(a, b + size - 8) = size | (alloc ? 1 : 0);
}
inline uint64_t& free_next(Arena& a, uint64_t b) {
  return *reinterpret_cast<uint64_t*>(a.base + b + 8);
}
inline uint64_t& free_prev(Arena& a, uint64_t b) {
  return *reinterpret_cast<uint64_t*>(a.base + b + 16);
}

void free_insert(Arena& a, uint64_t b) {
  ArenaHeader* h = hdr(a);
  free_next(a, b) = h->free_head;
  free_prev(a, b) = 0;
  if (h->free_head) free_prev(a, h->free_head) = b;
  h->free_head = b;
}

void free_remove(Arena& a, uint64_t b) {
  ArenaHeader* h = hdr(a);
  uint64_t nx = free_next(a, b), pv = free_prev(a, b);
  if (pv) free_next(a, pv) = nx; else h->free_head = nx;
  if (nx) free_prev(a, nx) = pv;
}

void heap_init(Arena& a) {
  ArenaHeader* h = hdr(a);
  uint64_t p = h->heap_off;
  set_tags(a, p, 16, true);  // prologue
  uint64_t big = p + 16;
  uint64_t big_size = (h->heap_end - 8) - big;  // leave 8 for epilogue tag
  big_size &= ~(kAlign - 1);
  set_tags(a, big, big_size, false);
  tag_at(a, big + big_size) = 0 | 1ull;  // epilogue: size 0, allocated
  h->epilogue_off = big + big_size;
  h->free_head = 0;
  free_insert(a, big);
}

// Returns block offset or 0 on OOM. size = total block size (already padded).
uint64_t heap_alloc(Arena& a, uint64_t need) {
  uint64_t b = hdr(a)->free_head;
  while (b) {
    uint64_t sz = blk_size(a, b);
    if (sz >= need) {
      free_remove(a, b);
      if (sz - need >= kMinBlock) {
        set_tags(a, b, need, true);
        uint64_t rest = b + need;
        set_tags(a, rest, sz - need, false);
        free_insert(a, rest);
      } else {
        set_tags(a, b, sz, true);
      }
      return b;
    }
    b = free_next(a, b);
  }
  return 0;
}

void heap_free(Arena& a, uint64_t b) {
  uint64_t sz = blk_size(a, b);
  // coalesce right
  uint64_t right = b + sz;
  if (!blk_alloc(a, right)) {
    free_remove(a, right);
    sz += blk_size(a, right);
  }
  // coalesce left
  uint64_t left_ftr = b - 8;
  if (!(tag_at(a, left_ftr) & 1ull)) {
    uint64_t lsz = tag_at(a, left_ftr) & ~1ull;
    uint64_t left = b - lsz;
    free_remove(a, left);
    b = left;
    sz += lsz;
  }
  set_tags(a, b, sz, false);
  free_insert(a, b);
}

// ------------------------------- index --------------------------------------

uint64_t fnv1a(const uint8_t* p, size_t n) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; i++) { h ^= p[i]; h *= 1099511628211ull; }
  return h;
}

int hex_to_id(const char* hex, uint8_t out[kIdBytes]) {
  for (uint32_t i = 0; i < kIdBytes; i++) {
    int v = 0;
    for (int j = 0; j < 2; j++) {
      char c = hex[2 * i + j];
      int d;
      if (c >= '0' && c <= '9') d = c - '0';
      else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
      else return -1;
      v = (v << 4) | d;
    }
    out[i] = (uint8_t)v;
  }
  return 0;
}

// Find entry for id; returns slot index or -1. If insert, returns first
// usable slot (empty/tombstone) when the id is absent.
int64_t index_find(Arena& a, const uint8_t id[kIdBytes], bool insert) {
  ArenaHeader* h = hdr(a);
  Entry* idx = index_of(a);
  uint32_t slots = h->index_slots;
  uint64_t start = fnv1a(id, kIdBytes) & (slots - 1);
  int64_t first_free = -1;
  for (uint32_t i = 0; i < slots; i++) {
    uint32_t s = (start + i) & (slots - 1);
    Entry& e = idx[s];
    if (e.state == kEmpty) {
      if (insert) return first_free >= 0 ? first_free : s;
      return -1;
    }
    if (e.state == kTomb) {
      if (first_free < 0) first_free = s;
      continue;
    }
    if (memcmp(e.id, id, kIdBytes) == 0) return s;
  }
  return insert ? first_free : -1;
}

// Crash recovery after EOWNERDEAD: a process died while mutating the heap
// free list or index. Header tags are the authority — rebuild the free list
// (coalescing adjacent free blocks), recompute stats, and tomb index entries
// whose block no longer looks like a live allocation. If the tags themselves
// are torn, freeze the allocator (free_head = 0): existing sealed objects
// stay readable and new puts fall back to the portable store.
void crash_recover(Arena& a) {
  ArenaHeader* h = hdr(a);
  uint64_t heap_lo = h->heap_off + 16;  // past prologue
  // pass 1: validate the block walk and merge runs of free blocks
  uint64_t b = heap_lo, run = 0;
  bool valid = true;
  while (true) {
    if (b + 8 > h->heap_end) { valid = false; break; }
    uint64_t t = tag_at(a, b);
    uint64_t sz = t & ~1ull;
    if (sz == 0) {
      // Only the true terminator may read zero: a torn split in heap_alloc
      // leaves a zero tag mid-heap, which must freeze, not truncate.
      valid = (b == h->epilogue_off);
      break;
    }
    if (sz < 16 || (sz & 7) || b + sz + 8 > h->heap_end) { valid = false; break; }
    if (t & 1ull) {
      if (run) { set_tags(a, run, b - run, false); run = 0; }
    } else if (!run) {
      run = b;
    }
    b += sz;
  }
  if (run && valid) set_tags(a, run, b - run, false);
  if (!valid) { h->free_head = 0; return; }
  // pass 2: rebuild the free list and bytes_in_use from the merged walk
  h->free_head = 0;
  uint64_t in_use = 0;
  for (b = heap_lo;;) {
    uint64_t t = tag_at(a, b);
    uint64_t sz = t & ~1ull;
    if (sz == 0) break;
    if (t & 1ull) in_use += sz; else free_insert(a, b);
    b += sz;
  }
  h->bytes_in_use = in_use;
  // pass 3: index entries must point at live allocated blocks
  Entry* idx = index_of(a);
  uint64_t nobj = 0, ntomb = 0;
  for (uint32_t sl = 0; sl < h->index_slots; sl++) {
    Entry& e = idx[sl];
    if (e.state == kCreated || e.state == kSealed) {
      // Guard against a torn create (state written, off still 0): the
      // subtraction below must not wrap.
      if (e.off < heap_lo + 8 || e.off >= h->heap_end) {
        e.state = kTomb; e.pins = 0; e.deletable = 0;
        ntomb++;
        continue;
      }
      uint64_t bb = e.off - 8;
      bool ok = blk_alloc(a, bb) && blk_size(a, bb) >= e.size + 16 &&
                bb + blk_size(a, bb) <= h->heap_end;
      if (!ok) { e.state = kTomb; e.pins = 0; e.deletable = 0; }
      else nobj++;
    }
    if (e.state == kTomb) ntomb++;
  }
  h->num_objects = nobj;
  h->num_tombs = ntomb;
}

struct LockGuard {
  pthread_mutex_t* m;
  explicit LockGuard(Arena& a) : m(&hdr(a)->mutex) {
    int rc = pthread_mutex_lock(m);
    if (rc == EOWNERDEAD) {
      crash_recover(a);
      pthread_mutex_consistent(m);
    }
  }
  ~LockGuard() { pthread_mutex_unlock(m); }
};

// Linear-probe tombstones are only reusable for inserts, not terminators:
// once most slots are tombs every miss scans the whole index under the
// mutex. Rebuild in place once tombs pass 1/4 of slots.
void maybe_rehash(Arena& a) {
  ArenaHeader* h = hdr(a);
  uint32_t slots = h->index_slots;
  if (h->num_tombs * 4 < slots) return;
  // Crash safety: rebuild into the inactive region, then flip active_index
  // with one aligned store. A process dying mid-rebuild leaves the active
  // region untouched.
  Entry* idx = index_of(a);
  Entry* fresh = index_inactive(a);
  memset(fresh, 0, (size_t)slots * sizeof(Entry));
  for (uint32_t sl = 0; sl < slots; sl++) {
    Entry& e = idx[sl];
    if (e.state != kCreated && e.state != kSealed) continue;
    uint64_t start = fnv1a(e.id, kIdBytes) & (slots - 1);
    for (uint32_t j = 0; j < slots; j++) {
      uint32_t t = (start + j) & (slots - 1);
      if (fresh[t].state == kEmpty) { fresh[t] = e; break; }
    }
  }
  __sync_synchronize();
  h->active_index ^= 1;  // atomic publish
  h->num_tombs = 0;
}

void entry_reclaim_locked(Arena& a, Entry& e) {
  ArenaHeader* h = hdr(a);
  uint64_t b = e.off - 8;
  h->bytes_in_use -= blk_size(a, b);
  h->num_objects -= 1;
  heap_free(a, b);
  e.state = kTomb;
  e.pins = 0;
  e.deletable = 0;
  e.creator_client = 0xFF;
  e.creator_unpinned = 0;
  h->num_tombs += 1;
  maybe_rehash(a);
}

// Subtract a dead client's ledger from the entries and free its slot.
// Caller holds the arena mutex.
void scrub_client_locked(Arena& a, uint32_t c) {
  ArenaHeader* h = hdr(a);
  PinRec* tab = pin_ledger(a, c);
  for (uint32_t i = 0; i < h->pin_slots; i++) {
    PinRec& r = tab[i];
    if (r.count == 0) continue;
    int64_t sl = index_find(a, r.id, false);
    if (sl >= 0) {
      Entry& e = index_of(a)[sl];
      if (e.state == kCreated || e.state == kSealed) {
        uint32_t sub = r.count < e.pins ? r.count : e.pins;
        e.pins -= sub;
        if (e.creator_client == c) {
          // The subtract just replayed the creator's +1 (if still held):
          // a later rt_obj_delete must not drop a reader's pin for it.
          e.creator_unpinned = 1;
        }
        if (e.state == kCreated) {
          // creator died before seal: the object can never be read
          e.deletable = 1;
        }
        if (e.pins == 0 && e.deletable) entry_reclaim_locked(a, e);
      }
    }
    r.count = 0;
  }
  memset(tab, 0, (size_t)h->pin_slots * sizeof(PinRec));
  ClientSlot& cs = clients_of(a)[c];
  cs.state = 0;
  cs.pid = 0;
  cs.starttime = 0;
}

// Reclaim pins owned by processes that no longer exist.
void scrub_dead_clients_locked(Arena& a, int self_client) {
  ClientSlot* cs = clients_of(a);
  bool scrubbed = false;
  for (uint32_t c = 0; c < kMaxClients; c++) {
    if ((int)c == self_client || cs[c].state != 1) continue;
    if (!process_alive(cs[c].pid, cs[c].starttime)) {
      scrub_client_locked(a, c);
      scrubbed = true;
    }
  }
  if (scrubbed) {
    // A process that died inside rt_arena_copy leaked its active_copiers
    // increment; reset the advisory counter (a live copier's budget reads
    // too big for one copy — harmless).
    __atomic_store_n(&hdr(a)->active_copiers, 0, __ATOMIC_RELAXED);
  }
}

// Claim a ClientSlot for this process (reusing dead slots). Caller holds
// the arena mutex. Returns slot or -1 (table full of live processes).
int claim_client_locked(Arena& a) {
  ClientSlot* cs = clients_of(a);
  uint32_t mypid = (uint32_t)getpid();
  for (uint32_t c = 0; c < kMaxClients; c++) {
    if (cs[c].state == 1 && cs[c].pid == mypid &&
        cs[c].starttime == read_starttime(mypid)) {
      return (int)c;  // re-attach from the same process
    }
  }
  for (uint32_t c = 0; c < kMaxClients; c++) {
    if (cs[c].state == 0) {
      cs[c].state = 1;
      cs[c].pid = mypid;
      cs[c].starttime = read_starttime(mypid);
      memset(pin_ledger(a, c), 0,
             (size_t)hdr(a)->pin_slots * sizeof(PinRec));
      return (int)c;
    }
  }
  // all slots claimed: scrub the dead and retry once
  scrub_dead_clients_locked(a, -1);
  for (uint32_t c = 0; c < kMaxClients; c++) {
    if (cs[c].state == 0) {
      cs[c].state = 1;
      cs[c].pid = mypid;
      cs[c].starttime = read_starttime(mypid);
      memset(pin_ledger(a, c), 0,
             (size_t)hdr(a)->pin_slots * sizeof(PinRec));
      return (int)c;
    }
  }
  return -1;
}

// ---------------------------------------------------------------- prefault

#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23
#endif

constexpr uint64_t kPopulateChunk = 512ull << 20;  // per background pass
constexpr uint64_t kPopulateAhead = 256ull << 20;  // slack before re-kick

std::atomic<bool> g_populating[kMaxArenas];

void populate_range(uint8_t* base, uint64_t from, uint64_t to) {
  if (madvise(base + from, to - from, MADV_POPULATE_WRITE) == 0) return;
  // Old kernel: write-touch one byte per page (OR 0 dirties without
  // changing content; the kernel zeroes on first touch either way).
  for (uint64_t off = from; off < to; off += 4096) {
    __atomic_fetch_or(base + off, (uint8_t)0, __ATOMIC_RELAXED);
  }
}

// How much to fault per unlocked slice. Bounds how long rt_arena_detach can
// wait on the pin count (one slice of fault time, not the whole pass).
constexpr uint64_t kPopulateSlice = 64ull << 20;

// Keep the faulted watermark ahead of the allocation frontier. Called
// WITHOUT the arena mutex; one background thread per process per arena.
// The thread takes g_table_mutex only to pin the mapping per slice; the
// page faults themselves run unlocked so attach/create/detach of OTHER
// arenas (and this one, until detach) never stall behind tmpfs fault rates.
void maybe_populate(int handle, uint64_t need_to) {
  Arena& a = g_arenas[handle];
  ArenaHeader* h = hdr(a);
  uint64_t cur = __atomic_load_n(&h->populated_to, __ATOMIC_ACQUIRE);
  if (cur >= h->heap_end) return;
  if (need_to + kPopulateAhead <= cur) return;
  bool expect = false;
  if (!g_populating[handle].compare_exchange_strong(expect, true)) return;
  uint64_t my_gen = a.gen;
  std::thread([handle, need_to, my_gen] {
    // One bounded pass: the target is fixed up front (cur + chunk, at least
    // need_to + ahead, capped at heap_end) — NOT recomputed per slice, which
    // would fault the entire arena eagerly and commit all its tmpfs pages.
    uint64_t target = 0;
    for (;;) {
      uint8_t* base;
      uint64_t from, to;
      {
        // Pin under the table mutex: detach sets used=false first (blocking
        // new pins), then waits for the pin count to hit zero before munmap.
        // The generation check keeps a thread that outlived its arena from
        // populating a NEW arena that reused this slot with a stale target.
        std::lock_guard<std::mutex> tg(g_table_mutex);
        Arena& a = g_arenas[handle];
        if (!a.used || a.gen != my_gen) break;
        ArenaHeader* h = hdr(a);
        uint64_t cur = __atomic_load_n(&h->populated_to, __ATOMIC_ACQUIRE);
        if (target == 0) {
          target = cur + kPopulateChunk;
          if (target < need_to + kPopulateAhead) {
            target = need_to + kPopulateAhead;
          }
          if (target > h->heap_end) target = h->heap_end;
        }
        if (cur >= target) break;
        from = cur;
        to = from + kPopulateSlice < target ? from + kPopulateSlice : target;
        base = a.base;
        g_arena_pin[handle].fetch_add(1, std::memory_order_acquire);
      }
      populate_range(base, from, to);
      ArenaHeader* h = reinterpret_cast<ArenaHeader*>(base);
      uint64_t prev = from;
      while (prev < to &&
             !__atomic_compare_exchange_n(&h->populated_to, &prev, to,
                                          false, __ATOMIC_RELEASE,
                                          __ATOMIC_RELAXED)) {
      }
      g_arena_pin[handle].fetch_sub(1, std::memory_order_release);
    }
    g_populating[handle].store(false);
  }).detach();
}

}  // namespace

// ------------------------------- C API --------------------------------------

extern "C" {

// Create the arena file (fails with -EEXIST if it already exists).
// capacity covers header + index + heap. index_slots must be a power of two.
int rt_arena_create(const char* name, uint64_t capacity, uint32_t index_slots) {
  if (index_slots == 0 || (index_slots & (index_slots - 1))) return -EINVAL;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return -errno;
  if (ftruncate(fd, (off_t)capacity) != 0) {
    int e = errno; close(fd); shm_unlink(name); return -e;
  }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) { shm_unlink(name); return -errno; }

  ArenaHeader* h = reinterpret_cast<ArenaHeader*>(base);
  memset(h, 0, sizeof(ArenaHeader));
  h->version = kVersion;
  h->index_slots = index_slots;
  h->capacity = capacity;
  h->index_off = align_up(sizeof(ArenaHeader), 64);
  uint64_t index_bytes = 2 * (uint64_t)index_slots * sizeof(Entry);  // A/B
  h->client_off = align_up(h->index_off + index_bytes, 64);
  uint32_t pin_slots = index_slots / 16;
  if (pin_slots < 256) pin_slots = 256;
  h->pin_slots = pin_slots;
  uint64_t client_bytes = kMaxClients * sizeof(ClientSlot)
      + (uint64_t)kMaxClients * pin_slots * sizeof(PinRec);
  h->heap_off = align_up(h->client_off + client_bytes, 4096);
  h->heap_end = capacity;
  if (h->heap_off + (1 << 16) > h->heap_end) { munmap(base, capacity); shm_unlink(name); return -EINVAL; }
  memset((uint8_t*)base + h->index_off, 0, index_bytes);
  memset((uint8_t*)base + h->client_off, 0, client_bytes);

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  std::lock_guard<std::mutex> tg(g_table_mutex);
  int slot = table_claim_slot();
  if (slot < 0) { munmap(base, capacity); shm_unlink(name); return -ENOMEM; }
  Arena& a = g_arenas[slot];
  a.base = (uint8_t*)base;
  a.capacity = capacity;
  memset(a.name, 0, sizeof(a.name));
  strncpy(a.name, name, sizeof(a.name) - 1);
  heap_init(a);
  advise_hugepages(base, h->heap_off, h->heap_end);
  h->populated_to = h->heap_off;
  a.client = claim_client_locked(a);
  __sync_synchronize();
  h->magic = kMagic;  // publish: attachers spin on magic
  maybe_populate(slot, h->heap_off);  // warm the first chunk in background
  return slot;
}

// Attach an existing arena; returns handle or negative errno.
int rt_arena_attach(const char* name) {
  {
    std::lock_guard<std::mutex> tg(g_table_mutex);
    for (int i = 0; i < kMaxArenas; i++) {
      if (g_arenas[i].used && strcmp(g_arenas[i].name, name) == 0) return i;
    }
  }
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) { int e = errno; close(fd); return -e; }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return -errno;
  ArenaHeader* h = reinterpret_cast<ArenaHeader*>(base);
  if (h->magic != kMagic || h->version != kVersion) {
    munmap(base, st.st_size);
    return -EPROTO;
  }
  std::lock_guard<std::mutex> tg(g_table_mutex);
  int slot = table_claim_slot();
  if (slot < 0) { munmap(base, st.st_size); return -ENOMEM; }
  Arena& a = g_arenas[slot];
  a.base = (uint8_t*)base;
  a.capacity = (uint64_t)st.st_size;
  memset(a.name, 0, sizeof(a.name));
  strncpy(a.name, name, sizeof(a.name) - 1);
  advise_hugepages(base, h->heap_off, h->heap_end);
  {
    LockGuard g(a);
    a.client = claim_client_locked(a);
  }
  return slot;
}

int rt_arena_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

// Unmap this process's view and free the handle slot. Only safe once no
// zero-copy views into the mapping remain in this process.
int rt_arena_detach(int handle) {
  std::lock_guard<std::mutex> tg(g_table_mutex);
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  if (a.client >= 0) {
    LockGuard g(a);
    scrub_client_locked(a, (uint32_t)a.client);
    a.client = -1;
  }
  // Block new populate pins (the thread checks `used` under g_table_mutex),
  // then wait out at most one in-flight populate slice before unmapping.
  a.used = false;
  while (g_arena_pin[handle].load(std::memory_order_acquire) != 0) {
    std::this_thread::yield();
  }
  munmap(a.base, a.capacity);
  a.base = nullptr;
  a.capacity = 0;
  a.name[0] = 0;
  return 0;
}

// Base pointer for this process's mapping (Python builds memoryviews on it).
void* rt_arena_base(int handle) {
  if (!handle_ok(handle)) return nullptr;
  return g_arenas[handle].base;
}

uint64_t rt_arena_capacity(int handle) {
  if (!handle_ok(handle)) return 0;
  return g_arenas[handle].capacity;
}

// Allocate + register an object. Returns payload offset, or negative errno
// (-EEXIST id taken, -ENOSPC no contiguous space, -ENFILE index full).
int64_t rt_obj_create(int handle, const char* id_hex, uint64_t size) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  uint8_t id[kIdBytes];
  if (hex_to_id(id_hex, id) != 0) return -EINVAL;
  ArenaHeader* h = hdr(a);
  int64_t ret;
  uint64_t end_off = 0;
  {
    LockGuard g(a);
    int64_t s = index_find(a, id, /*insert=*/true);
    if (s < 0) return -ENFILE;
    Entry& e = index_of(a)[s];
    if (e.state == kCreated || e.state == kSealed) return -EEXIST;
    uint64_t need = align_up(size + 16, kAlign);  // +hdr/ftr tags
    if (need < kMinBlock) need = kMinBlock;
    uint64_t b = heap_alloc(a, need);
    if (b == 0) {
      // Space pressure: reclaim pins leaked by dead processes, then retry.
      scrub_dead_clients_locked(a, a.client);
      b = heap_alloc(a, need);
      if (b == 0) return -ENOSPC;
      // the scrub may have tombed/moved entries — re-resolve the slot
      s = index_find(a, id, /*insert=*/true);
      if (s < 0) { heap_free(a, b); return -ENFILE; }
    }
    Entry& e2 = index_of(a)[s];
    if (e2.state == kTomb && h->num_tombs > 0) h->num_tombs -= 1;
    memcpy(e2.id, id, kIdBytes);
    e2.state = kCreated;
    e2.deletable = 0;
    e2.creator_client = a.client >= 0 ? (uint8_t)a.client : 0xFF;
    e2.creator_unpinned = 0;
    e2.pins = 1;  // creator's pin; dropped (once) by rt_obj_delete
    e2.off = b + 8;
    e2.size = size;
    e2.seq = ++h->create_seq;
    h->bytes_in_use += blk_size(a, b);
    h->num_objects += 1;
    if (h->bytes_in_use > h->peak_bytes) h->peak_bytes = h->bytes_in_use;
    pin_log_add(a, a.client, id, +1);  // creator pin in this process's ledger
    ret = (int64_t)e2.off;
    end_off = e2.off + size;
  }
  // Outside the mutex: keep warm pages ahead of the allocation frontier.
  maybe_populate(handle, end_off);
  return ret;
}

int rt_obj_seal(int handle, const char* id_hex) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  uint8_t id[kIdBytes];
  if (hex_to_id(id_hex, id) != 0) return -EINVAL;
  LockGuard g(a);
  int64_t s = index_find(a, id, false);
  if (s < 0) return -ENOENT;
  Entry& e = index_of(a)[s];
  if (e.state != kCreated) return -EINVAL;
  e.state = kSealed;
  return 0;
}

// Pin + locate a sealed object. Returns payload offset (size in *size_out),
// -ENOENT if absent or not sealed yet.
int64_t rt_obj_get(int handle, const char* id_hex, uint64_t* size_out) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  uint8_t id[kIdBytes];
  if (hex_to_id(id_hex, id) != 0) return -EINVAL;
  LockGuard g(a);
  int64_t s = index_find(a, id, false);
  if (s < 0) return -ENOENT;
  Entry& e = index_of(a)[s];
  if (e.state != kSealed) return -ENOENT;
  e.pins += 1;
  pin_log_add(a, a.client, id, +1);
  if (size_out) *size_out = e.size;
  return (int64_t)e.off;
}

// Drop one pin (reader-side). Reclaims if deletable and pins hit zero.
int rt_obj_release(int handle, const char* id_hex) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  uint8_t id[kIdBytes];
  if (hex_to_id(id_hex, id) != 0) return -EINVAL;
  LockGuard g(a);
  int64_t s = index_find(a, id, false);
  if (s < 0) return -ENOENT;
  Entry& e = index_of(a)[s];
  if (e.pins == 0) return -EINVAL;
  e.pins -= 1;
  pin_log_add(a, a.client, id, -1);
  if (e.pins == 0 && e.deletable) entry_reclaim_locked(a, e);
  return 0;
}

// Owner-side delete: drop the creator pin, mark deletable; memory returns to
// the free list once every reader pin is released.
int rt_obj_delete(int handle, const char* id_hex) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  uint8_t id[kIdBytes];
  if (hex_to_id(id_hex, id) != 0) return -EINVAL;
  LockGuard g(a);
  int64_t s = index_find(a, id, false);
  if (s < 0) return -ENOENT;
  Entry& e = index_of(a)[s];
  if (e.state != kCreated && e.state != kSealed) return -ENOENT;
  e.deletable = 1;
  // Drop the creator pin exactly ONCE, no matter how many clients call
  // delete (owner free + creator free both land here). The -1 is logged
  // against the CREATOR's ledger — where the +1 lives — so a dead-client
  // scrub replays to the same balance.
  if (!e.creator_unpinned) {
    e.creator_unpinned = 1;
    if (e.pins > 0) e.pins -= 1;
    if (e.creator_client != 0xFF) {
      pin_log_add(a, (int)e.creator_client, id, -1);
    }
  }
  if (e.pins == 0) entry_reclaim_locked(a, e);
  return 0;
}

int rt_obj_contains(int handle, const char* id_hex) {
  if (!handle_ok(handle)) return 0;
  Arena& a = g_arenas[handle];
  uint8_t id[kIdBytes];
  if (hex_to_id(id_hex, id) != 0) return 0;
  LockGuard g(a);
  int64_t s = index_find(a, id, false);
  if (s < 0) return 0;
  return index_of(a)[s].state == kSealed ? 1 : 0;
}

// Test-only: grab the arena mutex and never release it. A test child calls
// this and _exits to simulate a crash inside the critical section, so the
// parent's next lock sees EOWNERDEAD and runs crash_recover.
int rt_test_hold_lock(int handle) {
  if (!handle_ok(handle)) return -EBADF;
  int rc = pthread_mutex_lock(&hdr(g_arenas[handle])->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&hdr(g_arenas[handle])->mutex);
  return 0;
}

// Reclaim pins held by dead processes (also runs automatically when a
// create hits ENOSPC). Returns number of live clients after the scrub.
int rt_arena_scrub(int handle) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  LockGuard g(a);
  scrub_dead_clients_locked(a, a.client);
  int live = 0;
  ClientSlot* cs = clients_of(a);
  for (uint32_t c = 0; c < kMaxClients; c++) live += cs[c].state == 1;
  return live;
}

uint64_t rt_arena_num_tombs(int handle) {
  if (!handle_ok(handle)) return 0;
  Arena& a = g_arenas[handle];
  LockGuard g(a);
  return hdr(a)->num_tombs;
}

void rt_arena_stats(int handle, uint64_t* bytes_in_use, uint64_t* num_objects,
                    uint64_t* capacity, uint64_t* peak_bytes) {
  if (!handle_ok(handle)) return;
  Arena& a = g_arenas[handle];
  ArenaHeader* h = hdr(a);
  LockGuard g(a);
  if (bytes_in_use) *bytes_in_use = h->bytes_in_use;
  if (num_objects) *num_objects = h->num_objects;
  if (capacity) *capacity = h->heap_end - h->heap_off;
  if (peak_bytes) *peak_bytes = h->peak_bytes;
}

// Non-temporal streaming copy. A regular memcpy into cold shm pages costs
// ~3 bytes of DRAM traffic per byte copied (src read + dst RFO read + dst
// write); streaming stores skip the RFO, cutting traffic to 2 bytes/byte —
// worth 1.2-1.5x on large object writes that will be read from DRAM by a
// different process anyway (the consumer maps the arena fresh, so polluting
// this core's cache with dst lines has no upside).
static void copy_stream_one(uint8_t* dst, const uint8_t* src, uint64_t n) {
#if defined(__x86_64__) || defined(__i386__)
  while ((((uintptr_t)dst) & 15) && n) { *dst++ = *src++; n--; }
  uint64_t blocks = n / 64;
  for (uint64_t i = 0; i < blocks; i++) {
    __m128i a = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + 0));
    __m128i b = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + 16));
    __m128i c = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + 32));
    __m128i d = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + 48));
    _mm_stream_si128(reinterpret_cast<__m128i*>(dst + 0), a);
    _mm_stream_si128(reinterpret_cast<__m128i*>(dst + 16), b);
    _mm_stream_si128(reinterpret_cast<__m128i*>(dst + 32), c);
    _mm_stream_si128(reinterpret_cast<__m128i*>(dst + 48), d);
    src += 64; dst += 64;
  }
  _mm_sfence();
  memcpy(dst, src, n - blocks * 64);
#else
  memcpy(dst, src, n);
#endif
}

// Copy with `budget` as the max thread count; each extra thread needs
// >=4MB of work before it pays for its ~25us spawn cost.
// Streaming (non-temporal) stores win once the copy clearly exceeds the
// LLC (no RFO: 2 bytes of DRAM traffic per byte instead of 3); below that,
// cached regular stores win because the arena reuses freed blocks whose
// lines may still be resident. Which side of that trade a ≥16MB copy lands
// on varies by machine (glibc may already stream internally), so unless
// RT_STREAM_MIN_MB pins the threshold, the first large copy runs a one-time
// in-process probe and the winner sticks.
static bool decide_stream(uint64_t len) {
  static const uint64_t env_min = [] {
    const char* s = getenv("RT_STREAM_MIN_MB");
    if (s && *s) {
      char* end = nullptr;
      long v = strtol(s, &end, 10);
      if (end != s && *end == '\0') {  // unparseable input → auto, not "0"
        if (v > 0) return (uint64_t)v << 20;
        if (v == 0) return (uint64_t)-1;  // explicit 0 = never stream
      }
    }
    return (uint64_t)0;  // unset/invalid = auto-calibrate
  }();
  if (env_min) return len >= env_min;
  constexpr uint64_t kAutoMin = 16ull << 20;
  if (len < kAutoMin) return false;
  static const bool stream_wins = [] {
    constexpr uint64_t probe = 16ull << 20;
    uint8_t* s = static_cast<uint8_t*>(malloc(probe));
    uint8_t* d = static_cast<uint8_t*>(malloc(probe));
    if (!s || !d) { free(s); free(d); return false; }
    memset(s, 1, probe);
    memset(d, 0, probe);  // prefault
    auto bench = [&](bool stream) {
      struct timespec a, b;
      double best = 1e99;
      for (int r = 0; r < 3; r++) {
        clock_gettime(CLOCK_MONOTONIC, &a);
        if (stream) copy_stream_one(d, s, probe); else memcpy(d, s, probe);
        clock_gettime(CLOCK_MONOTONIC, &b);
        double t = (b.tv_sec - a.tv_sec) + (b.tv_nsec - a.tv_nsec) * 1e-9;
        if (t < best) best = t;
      }
      return best;
    };
    double t_mc = bench(false);
    double t_nt = bench(true);
    double t_mc2 = bench(false);  // settle turbo/page-fault noise
    if (t_mc2 < t_mc) t_mc = t_mc2;
    free(s); free(d);
    return t_nt < t_mc;
  }();
  return stream_wins;
}

static void copy_parallel(void* dst, const void* src, uint64_t len,
                          unsigned budget) {
  constexpr uint64_t kPerThread = 4ull << 20;
  const bool stream = decide_stream(len);
  unsigned by_len = (unsigned)(len / kPerThread);
  unsigned nthreads = by_len < budget ? by_len : budget;
  if (nthreads <= 1) {
    if (stream) {
      copy_stream_one(static_cast<uint8_t*>(dst),
                      static_cast<const uint8_t*>(src), len);
    } else {
      memcpy(dst, src, len);
    }
    return;
  }
  // ceil-divide BEFORE aligning: flooring first can leave
  // chunk * nthreads < len (when the floor is already 64-aligned and len
  // isn't divisible by nthreads), silently dropping the payload tail.
  uint64_t chunk = ((len + nthreads - 1) / nthreads + 63) & ~63ull;
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  for (unsigned i = 1; i < nthreads; i++) {
    uint64_t off = static_cast<uint64_t>(i) * chunk;
    if (off >= len) break;
    uint64_t n = len - off < chunk ? len - off : chunk;
    ts.emplace_back([dst, src, off, n, stream] {
      if (stream) {
        copy_stream_one(static_cast<uint8_t*>(dst) + off,
                        static_cast<const uint8_t*>(src) + off, n);
      } else {
        memcpy(static_cast<uint8_t*>(dst) + off,
               static_cast<const uint8_t*>(src) + off, n);
      }
    });
  }
  // calling thread does the first chunk instead of idling in join
  uint64_t n0 = chunk < len ? chunk : len;
  if (stream) {
    copy_stream_one(static_cast<uint8_t*>(dst),
                    static_cast<const uint8_t*>(src), n0);
  } else {
    memcpy(dst, src, n0);
  }
  for (auto& t : ts) t.join();
}

static unsigned copy_budget_env() {
  static unsigned cached = [] {
    const char* s = getenv("RT_COPY_THREADS");
    if (s && *s) {
      long v = strtol(s, nullptr, 10);
      if (v >= 1 && v <= 64) return (unsigned)v;
    }
    unsigned hw = std::thread::hardware_concurrency();
    if (hw == 0) hw = 1;
    return hw < 8 ? hw : 8;
  }();
  return cached;
}

// Multi-threaded streaming memcpy — the uncoordinated building block for
// callers without an arena handle. No in-tree caller today (arena.py uses
// rt_arena_copy); kept as a stable C export for tools and tests.
void rt_memcpy_parallel(void* dst, const void* src, uint64_t len) {
  copy_parallel(dst, src, len, copy_budget_env());
}

// Arena-coordinated payload copy: concurrent putters (any process mapping
// this arena) share the machine's copy-thread budget instead of each
// spawning a full set — N clients each running 8-thread copies is how
// multi-client put throughput ends up BELOW single-client.
// `payload_off` is the offset returned by rt_obj_create (+ any frame-header
// bytes the caller has already written).
int rt_arena_copy(int handle, uint64_t payload_off, const void* src,
                  uint64_t len) {
  if (!handle_ok(handle)) return -EBADF;
  Arena& a = g_arenas[handle];
  ArenaHeader* h = hdr(a);
  uint32_t active = __atomic_add_fetch(&h->active_copiers, 1, __ATOMIC_ACQ_REL);
  // Clamp on READ only (the count is calls, not processes — executor
  // threads can legitimately push it past the client cap; large counts
  // just mean budget 1, which is the right behavior). Values beyond any
  // plausible live concurrency are leaks from crashed copiers; treat as 1
  // until the dead-client scrub resets the counter.
  uint32_t eff = (active == 0 || active > 1024) ? 1 : active;
  unsigned budget = copy_budget_env() / eff;
  if (budget < 1) budget = 1;
  copy_parallel(a.base + payload_off, src, len, budget);
  // Underflow-proof decrement: a concurrent scrub reset must not wrap the
  // counter to ~0 and wedge everyone's budget at 1 forever.
  uint32_t cur = __atomic_load_n(&h->active_copiers, __ATOMIC_RELAXED);
  while (cur != 0 &&
         !__atomic_compare_exchange_n(&h->active_copiers, &cur, cur - 1,
                                      false, __ATOMIC_ACQ_REL,
                                      __ATOMIC_RELAXED)) {
  }
  return 0;
}

}  // extern "C"
