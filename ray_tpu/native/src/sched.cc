// Native cluster resource scheduler.
//
// TPU-era equivalent of the reference's C++ scheduling stack
// (src/ray/common/scheduling/: FixedPoint `fixed_point.h`, interned
// resource ids `scheduling_ids.h`, `ResourceSet`/`NodeResources`
// `cluster_resource_data.h`; policy selection
// src/ray/raylet/scheduling/cluster_resource_scheduler.cc:155
// GetBestSchedulableNode and scheduling/policy/*:
// hybrid pack-then-spread, spread, node-affinity, node-label).
//
// Runs in-process inside the head service (single lease authority), loaded
// via ctypes. Resource quantities are fixed-point int64 (scale 1e4) so
// repeated acquire/release cycles can never drift the way float arithmetic
// does; resource names are interned to small ids once per scheduler so the
// hot best-node scan compares integers, not strings.
//
// Policy semantics intentionally match the Python fallback in
// ray_tpu/_private/gcs.py::HeadService._pick_node so the two paths are
// interchangeable and cross-checked by tests:
//   - candidates: alive, optional hard node-affinity, label equality, fits
//   - soft avoid-list: filtered only when an alternative fits
//   - pack (default): min (sum of available, node_id) — binpack onto the
//     most-utilized node, stable by id
//   - spread: round-robin cursor over fitting candidates

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr int64_t kScale = 10000;  // 1e-4 resource granularity

int64_t ToFixed(double v) {
  return static_cast<int64_t>(v * kScale + (v >= 0 ? 0.5 : -0.5));
}

double FromFixed(int64_t v) { return static_cast<double>(v) / kScale; }

struct Node {
  std::string id;
  bool alive = true;
  // Indexed by interned resource id; missing ids mean 0.
  std::vector<int64_t> total;
  std::vector<int64_t> available;
  std::unordered_map<std::string, std::string> labels;

  int64_t Get(const std::vector<int64_t>& vec, size_t rid) const {
    return rid < vec.size() ? vec[rid] : 0;
  }
  void Set(std::vector<int64_t>& vec, size_t rid, int64_t v) {
    if (rid >= vec.size()) vec.resize(rid + 1, 0);
    vec[rid] = v;
  }
};

struct Sched {
  // Interned resource names (reference: scheduling_ids.h string interning).
  std::vector<std::string> resource_names;
  std::unordered_map<std::string, size_t> resource_ids;
  // Insertion-ordered nodes (matches Python dict iteration order).
  std::vector<Node> nodes;
  std::unordered_map<std::string, size_t> node_index;
  uint64_t rr = 0;  // spread round-robin cursor

  size_t InternResource(const std::string& name) {
    auto it = resource_ids.find(name);
    if (it != resource_ids.end()) return it->second;
    size_t id = resource_names.size();
    resource_names.push_back(name);
    resource_ids.emplace(name, id);
    return id;
  }

  Node* Find(const char* node_id) {
    auto it = node_index.find(node_id);
    return it == node_index.end() ? nullptr : &nodes[it->second];
  }
};

// A resolved resource demand: interned ids + fixed-point amounts.
struct Demand {
  std::vector<size_t> ids;
  std::vector<int64_t> amounts;
};

Demand ResolveDemand(Sched* s, const char** names, const double* vals, int n) {
  Demand d;
  d.ids.reserve(n);
  d.amounts.reserve(n);
  for (int i = 0; i < n; ++i) {
    d.ids.push_back(s->InternResource(names[i]));
    d.amounts.push_back(ToFixed(vals[i]));
  }
  return d;
}

bool Fits(const Node& node, const Demand& d) {
  for (size_t i = 0; i < d.ids.size(); ++i) {
    if (node.Get(node.available, d.ids[i]) < d.amounts[i]) return false;
  }
  return true;
}

int64_t SumAvailable(const Node& node) {
  int64_t sum = 0;
  for (int64_t v : node.available) sum += v;
  return sum;
}

}  // namespace

extern "C" {

void* rts_sched_new() { return new Sched(); }

void rts_sched_free(void* h) { delete static_cast<Sched*>(h); }

// Create (or reset) a node: clears resources/labels, marks alive.
// Mirrors head re-registration, which rebuilds NodeInfo from scratch.
int rts_sched_add_node(void* h, const char* node_id) {
  Sched* s = static_cast<Sched*>(h);
  Node* n = s->Find(node_id);
  if (n == nullptr) {
    s->node_index.emplace(node_id, s->nodes.size());
    s->nodes.emplace_back();
    n = &s->nodes.back();
    n->id = node_id;
  } else {
    n->total.clear();
    n->available.clear();
    n->labels.clear();
  }
  n->alive = true;
  return 0;
}

int rts_sched_remove_node(void* h, const char* node_id) {
  Sched* s = static_cast<Sched*>(h);
  auto it = s->node_index.find(node_id);
  if (it == s->node_index.end()) return -1;
  size_t idx = it->second;
  s->nodes.erase(s->nodes.begin() + idx);
  s->node_index.erase(it);
  for (auto& kv : s->node_index) {
    if (kv.second > idx) --kv.second;
  }
  return 0;
}

int rts_sched_set_alive(void* h, const char* node_id, int alive) {
  Node* n = static_cast<Sched*>(h)->Find(node_id);
  if (n == nullptr) return -1;
  n->alive = alive != 0;
  return 0;
}

// Sets a resource's total AND available (registration-time semantics).
int rts_sched_set_resource(void* h, const char* node_id, const char* name,
                           double total) {
  Sched* s = static_cast<Sched*>(h);
  Node* n = s->Find(node_id);
  if (n == nullptr) return -1;
  size_t rid = s->InternResource(name);
  int64_t v = ToFixed(total);
  n->Set(n->total, rid, v);
  n->Set(n->available, rid, v);
  return 0;
}

int rts_sched_set_label(void* h, const char* node_id, const char* key,
                        const char* val) {
  Node* n = static_cast<Sched*>(h)->Find(node_id);
  if (n == nullptr) return -1;
  n->labels[key] = val;
  return 0;
}

// Unconditional subtract (callers check fit first, as the head does);
// returns -1 only for unknown nodes.
int rts_sched_acquire(void* h, const char* node_id, const char** names,
                      const double* vals, int n) {
  Sched* s = static_cast<Sched*>(h);
  Node* node = s->Find(node_id);
  if (node == nullptr) return -1;
  Demand d = ResolveDemand(s, names, vals, n);
  for (size_t i = 0; i < d.ids.size(); ++i) {
    node->Set(node->available, d.ids[i],
              node->Get(node->available, d.ids[i]) - d.amounts[i]);
  }
  return 0;
}

int rts_sched_release(void* h, const char* node_id, const char** names,
                      const double* vals, int n) {
  Sched* s = static_cast<Sched*>(h);
  Node* node = s->Find(node_id);
  if (node == nullptr) return -1;
  Demand d = ResolveDemand(s, names, vals, n);
  for (size_t i = 0; i < d.ids.size(); ++i) {
    int64_t next = node->Get(node->available, d.ids[i]) + d.amounts[i];
    // Clamp to the registered total: a release the head never granted
    // (e.g. a lease finishing across a head restart) must not inflate
    // capacity (mirrors HeadService._node_release).
    int64_t cap = node->Get(node->total, d.ids[i]);
    if (next > cap) next = cap;
    node->Set(node->available, d.ids[i], next);
  }
  return 0;
}

double rts_sched_available(void* h, const char* node_id, const char* name) {
  Sched* s = static_cast<Sched*>(h);
  Node* node = s->Find(node_id);
  if (node == nullptr) return -1.0;
  auto it = s->resource_ids.find(name);
  if (it == s->resource_ids.end()) return 0.0;
  return FromFixed(node->Get(node->available, it->second));
}

int rts_sched_fits(void* h, const char* node_id, const char** names,
                   const double* vals, int n) {
  Sched* s = static_cast<Sched*>(h);
  Node* node = s->Find(node_id);
  if (node == nullptr) return 0;
  Demand d = ResolveDemand(s, names, vals, n);
  return Fits(*node, d) ? 1 : 0;
}

int rts_sched_num_nodes(void* h) {
  return static_cast<int>(static_cast<Sched*>(h)->nodes.size());
}

// Pick the best schedulable node (reference:
// cluster_resource_scheduler.cc:155 GetBestSchedulableNode).
//
//   spread         0 = hybrid pack, 1 = spread (round-robin)
//   affinity_node  hard node-affinity (NULL = any)
//   label_keys/vals  required label equalities
//   avoid          soft blocklist of node ids
//
// Returns 1 and writes the chosen node id into out (NUL-terminated) on
// success; 0 if nothing fits.
int rts_sched_best_node(void* h, const char** need_names,
                        const double* need_vals, int n_need, int spread,
                        const char* affinity_node, const char** label_keys,
                        const char** label_vals, int n_labels,
                        const char** avoid, int n_avoid, char* out,
                        int out_cap) {
  Sched* s = static_cast<Sched*>(h);
  Demand d = ResolveDemand(s, need_names, need_vals, n_need);

  std::vector<const Node*> fitting;
  for (const Node& node : s->nodes) {
    if (!node.alive) continue;
    if (affinity_node != nullptr && node.id != affinity_node) continue;
    bool labels_ok = true;
    for (int i = 0; i < n_labels; ++i) {
      auto it = node.labels.find(label_keys[i]);
      if (it == node.labels.end() || it->second != label_vals[i]) {
        labels_ok = false;
        break;
      }
    }
    if (!labels_ok) continue;
    if (!Fits(node, d)) continue;
    fitting.push_back(&node);
  }

  if (n_avoid > 0 && !fitting.empty()) {
    std::unordered_set<std::string> avoid_set;
    for (int i = 0; i < n_avoid; ++i) avoid_set.insert(avoid[i]);
    std::vector<const Node*> preferred;
    for (const Node* node : fitting) {
      if (avoid_set.find(node->id) == avoid_set.end()) preferred.push_back(node);
    }
    if (!preferred.empty()) fitting = std::move(preferred);
  }

  if (fitting.empty()) return 0;

  const Node* chosen;
  if (spread) {
    ++s->rr;
    chosen = fitting[s->rr % fitting.size()];
  } else {
    chosen = *std::min_element(
        fitting.begin(), fitting.end(), [](const Node* a, const Node* b) {
          int64_t sa = SumAvailable(*a), sb = SumAvailable(*b);
          if (sa != sb) return sa < sb;
          return a->id < b->id;
        });
  }
  size_t len = chosen->id.size();
  if (len + 1 > static_cast<size_t>(out_cap)) return 0;
  std::memcpy(out, chosen->id.c_str(), len + 1);
  return 1;
}

}  // extern "C"
