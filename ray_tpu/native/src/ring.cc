// Native same-host message channel: two lock-free SPSC byte rings in one
// POSIX shm segment, with futex doorbells.
//
// Reference behavior being reproduced (not copied): the reference's C++
// core_worker submits tasks and receives replies over its native RPC plane
// (src/ray/core_worker/core_worker.h:167, task_submission/
// normal_task_submitter.h:86) so the per-call cost is C++-side framing, not
// a Python event loop. Here the equivalent hot path is a shared-memory ring
// pair between two local processes (driver <-> worker): a message send is
// one memcpy + one atomic store + (at most) one futex wake, and a receive
// drains many messages per wakeup. Cross-host traffic keeps the TCP plane.
//
// Layout (offsets fixed at creation; maps can land anywhere):
//   Header | RingHdr A | RingHdr B | data A (cap) | data B (cap)
// Side A (creator) sends into ring A, receives from ring B; side B
// (attacher) the reverse. Each ring is single-producer single-consumer;
// multi-threaded callers serialize sends in the Python binding (ring.py
// NativeRing holds a threading.Lock around rt_ring_send).
//
// Record: u32 len | payload | pad to 4; records wrap circularly (the copy
// helpers split at the capacity boundary). Positions are monotonically
// increasing u64s (masked by cap on access), so empty/full tests never
// ambiguate.
//
// Crash-robustness: a peer death is detected out-of-band (the owner of the
// channel also holds a TCP connection whose teardown marks the peer dead and
// closes the ring); rt_ring_close wakes both doorbells so any blocked
// sender/receiver observes the closed flag and returns -EPIPE.

#include <errno.h>
#include <fcntl.h>
#include <linux/futex.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <initializer_list>
#include <new>

namespace {

constexpr uint64_t kMagic = 0x52545F52494E4731ull;  // "RT_RING1"

inline uint64_t align4(uint64_t n) { return (n + 3u) & ~3ull; }

int futex_wait(std::atomic<uint32_t>* addr, uint32_t expect, int timeout_ms) {
  struct timespec ts;
  struct timespec* tsp = nullptr;
  if (timeout_ms >= 0) {
    ts.tv_sec = timeout_ms / 1000;
    ts.tv_nsec = (timeout_ms % 1000) * 1000000L;
    tsp = &ts;
  }
  long rc = syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr),
                    FUTEX_WAIT, expect, tsp, nullptr, 0);
  if (rc == -1) return -errno;
  return 0;
}

void futex_wake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE,
          0x7fffffff, nullptr, nullptr, 0);
}

struct alignas(64) RingHdr {
  std::atomic<uint64_t> prod;     // bytes ever written (monotonic)
  std::atomic<uint32_t> prod_seq; // doorbell: bumped after each publish
  std::atomic<uint32_t> cons_waiting;
  char _pad0[48];
  std::atomic<uint64_t> cons;     // bytes ever consumed (monotonic)
  std::atomic<uint32_t> cons_seq; // doorbell: bumped after each consume
  std::atomic<uint32_t> prod_waiting;
  char _pad1[48];
};

struct SegHdr {
  std::atomic<uint64_t> magic;  // published last by the creator (release)
  uint32_t version;
  uint32_t cap;                    // per-direction data capacity (pow2)
  std::atomic<uint32_t> closed_a;  // side A called close
  std::atomic<uint32_t> closed_b;
  char _pad[40];
  RingHdr ring_a;  // A -> B
  RingHdr ring_b;  // B -> A
};

struct Handle {
  SegHdr* seg;
  uint8_t* data_a;
  uint8_t* data_b;
  uint64_t map_len;
  int side;  // 0 = A (creator), 1 = B (attacher)

  RingHdr* out_ring() const { return side == 0 ? &seg->ring_a : &seg->ring_b; }
  RingHdr* in_ring() const { return side == 0 ? &seg->ring_b : &seg->ring_a; }
  uint8_t* out_data() const { return side == 0 ? data_a : data_b; }
  uint8_t* in_data() const { return side == 0 ? data_b : data_a; }
  std::atomic<uint32_t>* my_closed() const {
    return side == 0 ? &seg->closed_a : &seg->closed_b;
  }
  std::atomic<uint32_t>* peer_closed() const {
    return side == 0 ? &seg->closed_b : &seg->closed_a;
  }
};

uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// Copy a record into the ring at byte position `pos` (monotonic), handling
// the circular boundary. cap is a power of two.
inline void ring_write(uint8_t* data, uint32_t cap, uint64_t pos,
                       const void* src, uint64_t len) {
  uint32_t off = static_cast<uint32_t>(pos & (cap - 1));
  uint64_t first = cap - off;
  if (first >= len) {
    memcpy(data + off, src, len);
  } else {
    memcpy(data + off, src, first);
    memcpy(data, static_cast<const uint8_t*>(src) + first, len - first);
  }
}

inline void ring_read(const uint8_t* data, uint32_t cap, uint64_t pos,
                      void* dst, uint64_t len) {
  uint32_t off = static_cast<uint32_t>(pos & (cap - 1));
  uint64_t first = cap - off;
  if (first >= len) {
    memcpy(dst, data + off, len);
  } else {
    memcpy(dst, data + off, first);
    memcpy(static_cast<uint8_t*>(dst) + first, data, len - first);
  }
}

}  // namespace

extern "C" {

// Create the channel segment (side A). cap must be a power of two; the
// segment holds two rings of `cap` data bytes each. Returns a handle or
// nullptr (errno in *err).
void* rt_ring_create(const char* name, uint32_t cap, int* err) {
  if (cap == 0 || (cap & (cap - 1)) != 0) {
    if (err) *err = EINVAL;
    return nullptr;
  }
  uint64_t len = sizeof(SegHdr) + 2ull * cap;
  int fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) {
    if (err) *err = errno;
    return nullptr;
  }
  if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
    if (err) *err = errno;
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    if (err) *err = errno;
    shm_unlink(name);
    return nullptr;
  }
  // Fresh shm pages are already zero-filled; placement-new formalizes the
  // lifetime of the atomics without a -Wclass-memaccess memset.
  SegHdr* seg = new (base) SegHdr();
  seg->version = 1;
  seg->cap = cap;
  // Publish: attachers spin until magic appears; the release store orders
  // cap/version before it (paired with the attacher's acquire load).
  seg->magic.store(kMagic, std::memory_order_release);
  Handle* h = new Handle{seg, reinterpret_cast<uint8_t*>(base) + sizeof(SegHdr),
                         reinterpret_cast<uint8_t*>(base) + sizeof(SegHdr) + cap,
                         len, 0};
  return h;
}

// Attach to an existing channel (side B).
void* rt_ring_attach(const char* name, int* err) {
  int fd = shm_open(name, O_RDWR, 0);
  if (fd < 0) {
    if (err) *err = errno;
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(SegHdr)) {
    if (err) *err = EINVAL;
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    if (err) *err = errno;
    return nullptr;
  }
  SegHdr* seg = static_cast<SegHdr*>(base);
  // The creator publishes magic last; an attacher racing creation spins
  // briefly rather than failing spuriously. Acquire pairs with the
  // creator's release so cap/version are visible once magic is.
  for (int i = 0;
       i < 1000 && seg->magic.load(std::memory_order_acquire) != kMagic; i++)
    usleep(1000);
  if (seg->magic.load(std::memory_order_acquire) != kMagic) {
    if (err) *err = EINVAL;
    munmap(base, st.st_size);
    return nullptr;
  }
  uint32_t cap = seg->cap;
  Handle* h = new Handle{seg, reinterpret_cast<uint8_t*>(base) + sizeof(SegHdr),
                         reinterpret_cast<uint8_t*>(base) + sizeof(SegHdr) + cap,
                         static_cast<uint64_t>(st.st_size), 1};
  return h;
}

// Send one message. Blocks while the ring lacks space (futex on the
// consumer doorbell). Returns 0, -EPIPE (peer closed), -ETIMEDOUT, or
// -EMSGSIZE (message can never fit). Single producer per side.
int rt_ring_send(void* hv, const void* buf, uint32_t len, int timeout_ms) {
  Handle* h = static_cast<Handle*>(hv);
  RingHdr* r = h->out_ring();
  uint32_t cap = h->seg->cap;
  uint64_t need = align4(4ull + len);
  if (need > cap) return -EMSGSIZE;
  uint64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : 0;
  uint64_t prod = r->prod.load(std::memory_order_relaxed);
  for (;;) {
    // Either side closing unblocks this sender (rt_ring_close wakes the
    // doorbells; the loop must then observe its OWN closed flag too).
    if (h->peer_closed()->load(std::memory_order_acquire) ||
        h->my_closed()->load(std::memory_order_acquire))
      return -EPIPE;
    uint64_t cons = r->cons.load(std::memory_order_acquire);
    if (cap - (prod - cons) >= need) break;
    uint32_t seq = r->cons_seq.load(std::memory_order_acquire);
    // Re-check after loading the doorbell (consume may have landed between).
    cons = r->cons.load(std::memory_order_acquire);
    if (cap - (prod - cons) >= need) break;
    r->prod_waiting.store(1, std::memory_order_seq_cst);
    int wait_ms = -1;
    if (timeout_ms >= 0) {
      uint64_t now = now_ms();
      if (now >= deadline) {
        r->prod_waiting.store(0, std::memory_order_relaxed);
        return -ETIMEDOUT;
      }
      wait_ms = static_cast<int>(deadline - now);
    }
    futex_wait(&r->cons_seq, seq, wait_ms);
    r->prod_waiting.store(0, std::memory_order_relaxed);
  }
  uint32_t len_le = len;
  ring_write(h->out_data(), cap, prod, &len_le, 4);
  ring_write(h->out_data(), cap, prod + 4, buf, len);
  r->prod.store(prod + align4(4ull + len), std::memory_order_release);
  r->prod_seq.fetch_add(1, std::memory_order_seq_cst);
  if (r->cons_waiting.load(std::memory_order_seq_cst)) {
    futex_wake(&r->prod_seq);
  }
  return 0;
}

// Receive up to max_msgs messages into buf; lens[i] receives each length.
// Blocks until at least one message (futex on producer doorbell). Returns
// the message count, 0 on timeout, -EPIPE when the peer closed and the ring
// is drained, or -EMSGSIZE if the next message exceeds buflen (nothing
// consumed; retry with a bigger buffer of at least lens[0] bytes).
int64_t rt_ring_recv_many(void* hv, void* buf, uint64_t buflen,
                          uint32_t max_msgs, uint32_t* lens, int timeout_ms) {
  Handle* h = static_cast<Handle*>(hv);
  RingHdr* r = h->in_ring();
  uint32_t cap = h->seg->cap;
  uint64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : 0;
  uint64_t cons = r->cons.load(std::memory_order_relaxed);
  for (;;) {
    uint64_t prod = r->prod.load(std::memory_order_acquire);
    if (prod != cons) break;
    // Order matters: read the closed flags BEFORE re-reading prod. A sender
    // publishes its final message (release) before closing (seq_cst), so if
    // closed is observed and the subsequent prod re-read still shows empty,
    // the ring is genuinely drained — the final message is never dropped.
    bool closed = h->peer_closed()->load(std::memory_order_acquire) ||
                  h->my_closed()->load(std::memory_order_acquire);
    uint32_t seq = r->prod_seq.load(std::memory_order_acquire);
    prod = r->prod.load(std::memory_order_acquire);
    if (prod != cons) break;
    if (closed) return -EPIPE;
    r->cons_waiting.store(1, std::memory_order_seq_cst);
    int wait_ms = -1;
    if (timeout_ms >= 0) {
      uint64_t now = now_ms();
      if (now >= deadline) {
        r->cons_waiting.store(0, std::memory_order_relaxed);
        return 0;
      }
      wait_ms = static_cast<int>(deadline - now);
    }
    futex_wait(&r->prod_seq, seq, wait_ms);
    r->cons_waiting.store(0, std::memory_order_relaxed);
  }
  uint64_t prod = r->prod.load(std::memory_order_acquire);
  uint8_t* out = static_cast<uint8_t*>(buf);
  uint64_t used = 0;
  int64_t count = 0;
  while (cons != prod && count < static_cast<int64_t>(max_msgs)) {
    uint32_t len;
    ring_read(h->in_data(), cap, cons, &len, 4);
    if (used + len > buflen) {
      if (count == 0) {
        lens[0] = len;
        return -EMSGSIZE;
      }
      break;
    }
    ring_read(h->in_data(), cap, cons + 4, out + used, len);
    lens[count] = len;
    used += len;
    count++;
    cons += align4(4ull + len);
  }
  r->cons.store(cons, std::memory_order_release);
  r->cons_seq.fetch_add(1, std::memory_order_seq_cst);
  if (r->prod_waiting.load(std::memory_order_seq_cst)) {
    futex_wake(&r->cons_seq);
  }
  return count;
}

// Mark this side closed and wake any thread blocked on either doorbell.
// The seq words must be BUMPED (not just woken): a blocker that loaded the
// closed flag and a seq value just before this call would otherwise
// futex_wait on an unchanged word and sleep through the wake.
void rt_ring_close(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  h->my_closed()->store(1, std::memory_order_seq_cst);
  for (RingHdr* r : {h->out_ring(), h->in_ring()}) {
    r->prod_seq.fetch_add(1, std::memory_order_seq_cst);
    r->cons_seq.fetch_add(1, std::memory_order_seq_cst);
    futex_wake(&r->prod_seq);
    futex_wake(&r->cons_seq);
  }
}

int rt_ring_peer_closed(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  return h->peer_closed()->load(std::memory_order_acquire) ? 1 : 0;
}

void rt_ring_detach(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  munmap(h->seg, h->map_len);
  delete h;
}

int rt_ring_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

}  // extern "C"
