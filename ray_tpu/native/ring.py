"""ctypes binding for the native shm message ring (src/ring.cc).

A ``RingChannel`` is one bidirectional same-host channel between two
processes: the server side creates it, the client side attaches. Sends are
serialized with a thread lock (the C ring is single-producer per direction);
receives happen on one pump thread per channel and drain many messages per
futex wakeup.

Reference analog (behavior, not code): the C++ core worker's native
submit/reply plane (``src/ray/core_worker/core_worker.h:167``) — the hot
task path never touches the Python event loop's socket machinery.
"""
from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import List, Optional

from ray_tpu import native as native_mod

logger = logging.getLogger(__name__)


def _native_ring_enabled() -> bool:
    from ray_tpu._private.config import rt_config

    return rt_config.native_ring

_DIR = os.path.dirname(os.path.abspath(native_mod.__file__))
_LIB_PATH = os.path.join(_DIR, "librt_ring.so")
_SRCS = [os.path.join(_DIR, "src", "ring.cc")]

_lock = threading.Lock()
_lib = None
_tried = False

DEFAULT_CAPACITY = 4 * 1024 * 1024  # per direction


def _load_library():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = native_mod.build_and_load("librt_ring.so", _LIB_PATH, _SRCS)
        if lib is None:
            return None
        lib.rt_ring_create.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_int),
        ]
        lib.rt_ring_create.restype = ctypes.c_void_p
        lib.rt_ring_attach.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ]
        lib.rt_ring_attach.restype = ctypes.c_void_p
        lib.rt_ring_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int,
        ]
        lib.rt_ring_send.restype = ctypes.c_int
        lib.rt_ring_recv_many.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
        ]
        lib.rt_ring_recv_many.restype = ctypes.c_int64
        lib.rt_ring_close.argtypes = [ctypes.c_void_p]
        lib.rt_ring_close.restype = None
        lib.rt_ring_peer_closed.argtypes = [ctypes.c_void_p]
        lib.rt_ring_peer_closed.restype = ctypes.c_int
        lib.rt_ring_detach.argtypes = [ctypes.c_void_p]
        lib.rt_ring_detach.restype = None
        lib.rt_ring_unlink.argtypes = [ctypes.c_char_p]
        lib.rt_ring_unlink.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return (
        _native_ring_enabled()
        and _load_library() is not None
    )


class RingClosed(Exception):
    pass


class RingFull(Exception):
    """Non-blocking send found no space (caller queues and retries)."""


class RingMessageTooBig(Exception):
    """Message exceeds ring capacity; caller must use another transport."""


class NativeRing:
    """One endpoint of a ring channel. Thread-safe sends; single receiver."""

    _RECV_BATCH = 128

    def __init__(self, name: str, create: bool,
                 capacity: int = DEFAULT_CAPACITY):
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native ring library unavailable")
        self._lib = lib
        self.name = name
        self.created = create
        self.capacity = capacity
        # Largest message this transport accepts; bigger payloads must ride
        # TCP (half the ring so one message can never deadlock the pipe).
        self.max_msg = capacity // 2
        err = ctypes.c_int(0)
        if create:
            self._h = lib.rt_ring_create(
                name.encode(), capacity, ctypes.byref(err)
            )
        else:
            self._h = lib.rt_ring_attach(name.encode(), ctypes.byref(err))
        if not self._h:
            raise OSError(err.value, os.strerror(err.value), name)
        self._send_lock = threading.Lock()
        self._recv_buf = ctypes.create_string_buffer(1 << 20)
        self._recv_lens = (ctypes.c_uint32 * self._RECV_BATCH)()
        self._closed = False

    def send(self, data: bytes, timeout_ms: int = -1):
        with self._send_lock:
            if self._h is None:
                raise RingClosed(f"ring {self.name}: detached")
            rc = self._lib.rt_ring_send(self._h, data, len(data), timeout_ms)
        if rc == 0:
            return
        if rc == -32:  # EPIPE
            raise RingClosed(f"ring {self.name}: peer closed")
        if rc == -110:  # ETIMEDOUT
            raise RingFull(f"ring {self.name}: full")
        if rc == -90:  # EMSGSIZE
            raise RingMessageTooBig(
                f"ring {self.name}: {len(data)}B message exceeds capacity"
            )
        raise OSError(-rc, os.strerror(-rc), f"ring send {self.name}")

    def recv_many(self, timeout_ms: int) -> Optional[List[bytes]]:
        """Drain up to a batch of messages; None on timeout; raises
        RingClosed when the peer closed and the ring is empty."""
        if self._h is None:
            raise RingClosed(f"ring {self.name}: detached")
        n = self._lib.rt_ring_recv_many(
            self._h, self._recv_buf, len(self._recv_buf),
            self._RECV_BATCH, self._recv_lens, timeout_ms,
        )
        if n == 0:
            return None
        if n == -32:  # EPIPE
            raise RingClosed(f"ring {self.name}: peer closed")
        if n == -90:  # EMSGSIZE: grow and retry (message already waiting)
            need = max(self._recv_lens[0] * 2, len(self._recv_buf) * 2)
            self._recv_buf = ctypes.create_string_buffer(need)
            return self.recv_many(timeout_ms)
        if n < 0:
            raise OSError(-n, os.strerror(-n), f"ring recv {self.name}")
        out = []
        pos = 0
        mv = memoryview(self._recv_buf)  # .raw would copy the whole buffer
        for i in range(n):
            ln = self._recv_lens[i]
            out.append(bytes(mv[pos:pos + ln]))
            pos += ln
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._h is not None:
            self._lib.rt_ring_close(self._h)

    def unlink_name(self):
        """Remove the /dev/shm name (creator side). Safe while mapped — the
        segment lives until the last mapping drops; without this, dead
        sessions leak tmpfs until reboot."""
        if self.created:
            self._lib.rt_ring_unlink(self.name.encode())

    def detach(self):
        """Unmap the segment. The receiver pump must have exited (close()
        wakes it); send/recv after detach raise RingClosed rather than
        handing C a dangling handle."""
        self.close()
        with self._send_lock:
            if self._h:
                self._lib.rt_ring_detach(self._h)
                self._h = None
        if self.created:
            self._lib.rt_ring_unlink(self.name.encode())

    def peer_closed(self) -> bool:
        return self._h is None or bool(
            self._lib.rt_ring_peer_closed(self._h)
        )
