"""Actor classes and handles (reference: ``python/ray/actor.py``)."""
from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional

from ray_tpu._private.worker import get_global_worker
from ray_tpu.remote_function import _build_resources, _build_strategy

_ACTOR_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "num_gpus",
    "resources",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "concurrency_groups",
    "name",
    "namespace",
    "get_if_exists",
    "lifetime",
    "scheduling_strategy",
    "runtime_env",
    "label_selector",
}


def method(*, concurrency_group: str = None, num_returns: int = None):
    """Decorator tagging an actor method with execution options (reference:
    ``ray.method`` — ``python/ray/actor.py``; concurrency groups:
    ``core_worker/task_execution/concurrency_group_manager.h:38``).

    ``concurrency_group`` routes the method onto the named group's executor
    declared via ``@remote(concurrency_groups={...})``, isolating it from
    other groups' slow calls (e.g. health checks vs. work lanes)."""

    def decorate(fn):
        if concurrency_group is not None:
            fn._rt_concurrency_group = concurrency_group
        if num_returns is not None:
            fn._rt_num_returns = num_returns
        return fn

    return decorate


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use .{self._method_name}.remote()."
        )

    def options(self, num_returns: int = None, concurrency_group: str = None,
                **_):
        return ActorMethod(
            self._handle,
            self._method_name,
            # None = keep the declared/@method value, don't reset
            self._num_returns if num_returns is None else num_returns,
            self._concurrency_group if concurrency_group is None
            else concurrency_group,
        )

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting (reference:
        ``dag/class_node.py`` — ``actor.method.bind``)."""
        if self._num_returns != 1:
            raise NotImplementedError(
                "bind() does not support num_returns != 1; return a tuple "
                "and split downstream"
            )
        from ray_tpu.dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id_hex,
            self._method_name,
            args,
            kwargs,
            num_returns=self._num_returns,
            max_task_retries=self._handle._max_task_retries,
            concurrency_group=self._concurrency_group,
        )
        if self._num_returns == 1:
            return refs[0]
        return refs


class ActorHandle:
    def __init__(self, actor_id_hex: str, addr=None, max_task_retries: int = 0,
                 class_name: str = "Actor",
                 method_meta: Optional[Dict[str, int]] = None):
        self._actor_id_hex = actor_id_hex
        self._addr = tuple(addr) if addr else None
        self._max_task_retries = max_task_retries
        self._class_name = class_name
        # method name -> declared num_returns (@method(num_returns=N))
        self._method_meta = method_meta or {}
        if addr is not None:
            try:
                get_global_worker().get_actor_channel(actor_id_hex, addr)
            except Exception:
                pass

    @property
    def _actor_id(self):
        return self._actor_id_hex

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(
            self, item, self._method_meta.get(item, 1)
        )

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id_hex[:16]})"

    def __reduce__(self):
        # A handle crossing a process boundary must be resolvable via the
        # head: wait out a still-batching deferred creation (no-op once
        # the create_actor_batch reply landed; never blocks a loop
        # thread — the receiver-side not-found grace covers that window).
        try:
            get_global_worker().ensure_actor_created(self._actor_id_hex)
        except Exception:
            pass
        return (
            ActorHandle,
            (self._actor_id_hex, self._addr, self._max_task_retries,
             self._class_name, self._method_meta),
        )


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        functools.update_wrapper(self, cls, updated=[])
        # Opt-in decoration-time static analysis (RAY_TPU_LINT=1); see
        # RemoteFunction.__init__ / ray_tpu.lint.
        if os.environ.get("RAY_TPU_LINT"):
            from ray_tpu.lint import check_actor_class, lint_enabled

            if lint_enabled():
                check_actor_class(cls, self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **opts) -> "ActorClass":
        bad = set(opts) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"unknown actor options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = get_global_worker()
        opts = self._options
        max_restarts = opts.get("max_restarts", 0)
        cgroups = opts.get("concurrency_groups")
        if cgroups is not None:
            if not isinstance(cgroups, dict) or not all(
                isinstance(k, str) and isinstance(v, int) and v > 0
                for k, v in cgroups.items()
            ):
                raise ValueError(
                    "concurrency_groups must be a dict of "
                    "{group_name: positive max_concurrency}, got "
                    f"{cgroups!r}"
                )
        # Walk the MRO so @method(num_returns=N) on inherited base-class
        # methods is honored too (vars() only sees the leaf class).
        method_meta: Dict[str, int] = {}
        for klass in reversed(type.mro(self._cls)):
            for name, fn in vars(klass).items():
                if callable(fn) and getattr(fn, "_rt_num_returns", None):
                    method_meta[name] = fn._rt_num_returns
        actor_id, addr, existing = worker.create_actor(
            self._cls,
            args,
            kwargs,
            resources=_build_resources(opts),
            strategy=_build_strategy(opts),
            max_restarts=max_restarts,
            max_concurrency=opts.get("max_concurrency", 1),
            concurrency_groups=cgroups,
            method_meta=method_meta,
            name=opts.get("name"),
            namespace=opts.get("namespace", "default"),
            get_if_exists=opts.get("get_if_exists", False),
            runtime_env=opts.get("runtime_env"),
            lifetime=opts.get("lifetime"),
        )
        return ActorHandle(
            actor_id if isinstance(actor_id, str) else actor_id.hex(),
            addr,
            opts.get("max_task_retries", 0),
            self._cls.__name__,
            method_meta,
        )

    @property
    def underlying_class(self):
        return self._cls


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (reference: ``ray.actor.exit_actor``)."""
    raise SystemExit(0)
