"""Actor classes and handles (reference: ``python/ray/actor.py``)."""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.worker import get_global_worker
from ray_tpu.remote_function import _build_resources, _build_strategy

_ACTOR_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "num_gpus",
    "resources",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "name",
    "namespace",
    "get_if_exists",
    "lifetime",
    "scheduling_strategy",
    "runtime_env",
    "label_selector",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use .{self._method_name}.remote()."
        )

    def options(self, num_returns: int = 1, **_):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting (reference:
        ``dag/class_node.py`` — ``actor.method.bind``)."""
        if self._num_returns != 1:
            raise NotImplementedError(
                "bind() does not support num_returns != 1; return a tuple "
                "and split downstream"
            )
        from ray_tpu.dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id_hex,
            self._method_name,
            args,
            kwargs,
            num_returns=self._num_returns,
            max_task_retries=self._handle._max_task_retries,
        )
        if self._num_returns == 1:
            return refs[0]
        return refs


class ActorHandle:
    def __init__(self, actor_id_hex: str, addr=None, max_task_retries: int = 0,
                 class_name: str = "Actor"):
        self._actor_id_hex = actor_id_hex
        self._addr = tuple(addr) if addr else None
        self._max_task_retries = max_task_retries
        self._class_name = class_name
        if addr is not None:
            try:
                get_global_worker().get_actor_channel(actor_id_hex, addr)
            except Exception:
                pass

    @property
    def _actor_id(self):
        return self._actor_id_hex

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id_hex[:16]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id_hex, self._addr, self._max_task_retries, self._class_name),
        )


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **opts) -> "ActorClass":
        bad = set(opts) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"unknown actor options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = get_global_worker()
        opts = self._options
        max_restarts = opts.get("max_restarts", 0)
        actor_id, addr, existing = worker.create_actor(
            self._cls,
            args,
            kwargs,
            resources=_build_resources(opts),
            strategy=_build_strategy(opts),
            max_restarts=max_restarts,
            max_concurrency=opts.get("max_concurrency", 1),
            name=opts.get("name"),
            namespace=opts.get("namespace", "default"),
            get_if_exists=opts.get("get_if_exists", False),
            runtime_env=opts.get("runtime_env"),
            lifetime=opts.get("lifetime"),
        )
        return ActorHandle(
            actor_id if isinstance(actor_id, str) else actor_id.hex(),
            addr,
            opts.get("max_task_retries", 0),
            self._cls.__name__,
        )

    @property
    def underlying_class(self):
        return self._cls


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (reference: ``ray.actor.exit_actor``)."""
    raise SystemExit(0)
