"""Pipeline parallelism: GPipe-style microbatch loop as one XLA program.

The reference's pipeline story is per-step RPC between actors through
compiled-graph channels (``python/ray/dag/compiled_dag_node.py:804`` +
shared-memory/NCCL channels); on TPU we compile the whole schedule into a
single program instead (SURVEY.md §7.8): the "stage" mesh axis holds L/S
layers each, activations hop stage→stage+1 with ``ppermute`` (one ICI
neighbor hop), and a ``lax.scan`` runs the fill/steady/drain schedule.

Differentiable end-to-end; combine freely with data/tensor axes.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    apply_stage: Callable[[Any, jax.Array], jax.Array],
    num_microbatches: int,
    axis: str = "stage",
    params_spec: Optional[Any] = None,
    x_spec: P = P(),
    collect_aux: bool = False,
):
    """Run ``x`` through S pipeline stages.

    stage_params: pytree whose leaves have leading dim [L] sharded over
    ``axis`` (each stage sees its [L/S] slice).
    x: [B, ...] activations (batch first). B % num_microbatches == 0.
    apply_stage(local_params, mb) applies one stage's layers to a microbatch;
    with ``collect_aux`` it returns (y, aux_scalar) and pipeline_apply
    returns (out, aux) where aux is the microbatch-mean of the per-stage
    scalars psum'd over the stage axis — the MoE load-balancing loss
    survives the microbatch loop instead of being dropped (bubble steps,
    which compute on zero/garbage activations, are masked out).

    Schedule: M + S - 1 steps; stage 0 injects microbatch i at step i; the
    last stage's result for microbatch i appears at step i + S - 1. Output is
    re-broadcast with a masked psum over the stage axis (negligible next to
    the matmuls for real models; keeps out_specs replicated on ``axis``).
    """
    S = mesh.shape[axis]
    if S == 1:
        return apply_stage(stage_params, x)
    M = num_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if params_spec is None:
        params_spec = jax.tree.map(lambda _: P(axis), stage_params)

    def staged(params_local, x_local):
        sidx = jax.lax.axis_index(axis)
        mb = x_local.shape[0] // M
        mbs = x_local.reshape((M, mb) + x_local.shape[1:])
        perm = [(i, (i + 1) % S) for i in range(S)]
        out0 = jnp.zeros_like(mbs)
        recv0 = jnp.zeros((mb,) + x_local.shape[1:], x_local.dtype)
        aux0 = jnp.float32(0.0)

        def step(carry, i):
            recv, outs, aux_acc = carry
            inject = mbs[jnp.minimum(i, M - 1)]
            cur = jnp.where(sidx == 0, inject, recv)
            if collect_aux:
                y, aux = apply_stage(params_local, cur)
                # Stage s sees real microbatches only during its window
                # [s, s + M): bubble-step routing statistics are garbage.
                valid = jnp.logical_and(i >= sidx, i < sidx + M)
                aux_acc = aux_acc + jnp.where(
                    valid, aux.astype(jnp.float32), 0.0
                )
            else:
                y = apply_stage(params_local, cur)
            # collect on the last stage once the pipe is full
            oidx = jnp.maximum(i - (S - 1), 0)
            updated = jax.lax.dynamic_update_slice(
                outs, y[None].astype(outs.dtype),
                (oidx,) + (0,) * (outs.ndim - 1),
            )
            take = jnp.logical_and(i >= S - 1, sidx == S - 1)
            outs = jnp.where(take, updated, outs)
            recv_next = jax.lax.ppermute(y, axis, perm)
            return (recv_next, outs, aux_acc), None

        (_, outs, aux_acc), _ = jax.lax.scan(
            step, (recv0, out0, aux0), jnp.arange(M + S - 1)
        )
        # Broadcast the last stage's buffer to every stage.
        outs = jax.lax.psum(
            jnp.where(sidx == S - 1, outs, jnp.zeros_like(outs)), axis
        )
        if collect_aux:
            # Sum over stages (each holds distinct layers), mean over the M
            # microbatches each stage processed.
            aux_total = jax.lax.psum(aux_acc, axis) / M
            return outs.reshape(x_local.shape), aux_total
        return outs.reshape(x_local.shape)

    out_specs = (x_spec, P()) if collect_aux else x_spec
    # Manual only over the stage axis: batch/tensor/fsdp shardings of the
    # activations and weights stay under XLA's automatic propagation.
    return shard_map(
        staged,
        mesh=mesh,
        axis_names={axis},
        in_specs=(params_spec, x_spec),
        out_specs=out_specs,
        check_vma=False,
    )(stage_params, x)
