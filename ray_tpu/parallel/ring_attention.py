"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference implements NO sequence parallelism anywhere (grep-verified in
SURVEY.md §5 — "absent in the reference"); this module is the TPU-native
answer the survey prescribes: the mesh's "seq" axis holds sequence chunks,
and attention runs as a ring over ICI neighbors (``ppermute`` is literally a
neighbor hop on the TPU torus), overlapping K/V transfer with blockwise
compute. Ulysses (head-sharded all-to-all) is the low-latency alternative
when heads ≥ ring size.

Both are shard_map programs over one mesh axis and differentiable end-to-end
(scan-based accumulation; online softmax in f32).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ray_tpu.ops.attention import NEG_INF


def _blockwise_piece(q, k, v, scale, q_chunk, kv_chunk, t_local, causal):
    """Attention logits piece between the local Q chunk and one K/V chunk,
    returning (unnormalized o, running max m, running denom l) inputs for
    online-softmax merging. Shapes: q [B,T,H,D], k/v [B,T,H,D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        # chunk-level: kv_chunk > q_chunk → fully masked;
        # kv_chunk == q_chunk → intra-chunk causal; else unmasked.
        q_pos = q_chunk * t_local + jax.lax.broadcasted_iota(
            jnp.int32, (t_local, t_local), 0
        )
        k_pos = kv_chunk * t_local + jax.lax.broadcasted_iota(
            jnp.int32, (t_local, t_local), 1
        )
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,T,1]
    # Guard fully-masked rows (exp(NEG_INF - NEG_INF) = 1 would poison l).
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(m <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m_safe.transpose(0, 2, 1, 3), l.transpose(0, 2, 1, 3)  # m,l → [B,T,H,1]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
    qkv_spec: Optional[P] = None,
) -> jax.Array:
    """Ring attention over a sharded sequence axis.

    q/k/v: [batch, seq, heads, head_dim] with seq sharded over ``axis``.
    Each step computes blockwise attention against the resident K/V chunk and
    rotates K/V one ICI hop (ppermute), accumulating with online softmax.
    """
    if qkv_spec is None:
        qkv_spec = P(("data", "fsdp"), axis, "tensor", None)
    n = mesh.shape[axis]
    if n == 1:
        from ray_tpu.ops.attention import attention_xla

        return attention_xla(q, k, v, causal=causal)

    scale = q.shape[-1] ** -0.5

    def local_fn(q, k, v):
        # q,k,v local chunks: [B, T/n, H, D]
        my = jax.lax.axis_index(axis)
        t_local = q.shape[1]
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, _):
            o_acc, m_acc, l_acc, k_cur, v_cur, src = carry
            o, m, l = _blockwise_piece(
                q, k_cur, v_cur, scale, my, src, t_local, causal
            )
            # online-softmax merge of (o_acc, m_acc, l_acc) with (o, m, l)
            m_new = jnp.maximum(m_acc, m)
            a1 = jnp.exp(m_acc - m_new)
            a2 = jnp.exp(m - m_new)
            o_new = o_acc * a1 + o * a2
            l_new = l_acc * a1 + l * a2
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            src_nxt = jax.lax.rem(src - 1 + n, n)
            return (o_new, m_new, l_new, k_nxt, v_nxt, src_nxt), None

        B, T, H, D = q.shape
        o0 = jnp.zeros((B, T, H, D), jnp.float32)
        m0 = jnp.full((B, T, H, 1), NEG_INF / 2, jnp.float32)
        l0 = jnp.zeros((B, T, H, 1), jnp.float32)
        (o, m, l, _, _, _), _ = jax.lax.scan(
            step, (o0, m0, l0, k, v, my), None, length=n
        )
        return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
    qkv_spec: Optional[P] = None,
    impl: str = "xla",
) -> jax.Array:
    """Ulysses-style sequence parallelism: all_to_all swaps the sharded axis
    from sequence to heads, runs full-sequence attention on 1/n of the heads,
    and swaps back. One all_to_all each way (lower latency than a ring when
    heads % n == 0 and the full sequence fits)."""
    if qkv_spec is None:
        qkv_spec = P(("data", "fsdp"), axis, "tensor", None)
    n = mesh.shape[axis]
    from ray_tpu.ops.attention import attention_xla, flash_attention

    if n == 1:
        return attention_xla(q, k, v, causal=causal)
    if q.shape[2] % n != 0:
        raise ValueError(f"heads {q.shape[2]} not divisible by {axis}={n}")

    def local_fn(q, k, v):
        # local: [B, T/n, H, D] → all_to_all → [B, T, H/n, D]
        def swap_in(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def swap_out(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qg, kg, vg = swap_in(q), swap_in(k), swap_in(v)
        if impl == "flash":
            o = flash_attention(qg, kg, vg, causal)
        else:
            o = attention_xla(qg, kg, vg, causal=causal)
        return swap_out(o)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v)
