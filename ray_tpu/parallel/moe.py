"""Mixture-of-Experts with expert parallelism (GShard/Switch formulation).

The reference delegates EP entirely to vLLM (SURVEY.md §2.3); here experts are
a mesh axis. We use the sharded-einsum dispatch formulation (the original
GShard/Switch TPU design): routing builds a dispatch one-hot
[tokens, experts, capacity]; einsums against it ARE the all-to-alls once the
expert dim is sharded — XLA lowers the dispatch/combine contractions to
``all_to_all`` collectives over ICI when experts live on the "expert" axis.
Fully differentiable; auxiliary load-balancing loss included.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    router_jitter: float = 0.0
    # "gelu" (Switch-style experts) | "swiglu" (Mixtral-style gated experts)
    activation: str = "gelu"
    # Dropless routing (Mixtral-style inference): every token reaches its
    # top-k experts, no capacity queues. Required for KV-cache decode to
    # reproduce full-forward outputs — capacity drops depend on the other
    # tokens in the batch, which differ between prefill and per-step decode.
    # The decode engine flips this on; training defaults to capacity
    # (bounded per-expert work => static shapes for the all-to-alls).
    dropless: bool = False

    def __post_init__(self):
        if self.activation not in ("gelu", "swiglu"):
            raise ValueError(
                f"MoEConfig.activation must be 'gelu' or 'swiglu', got "
                f"{self.activation!r}"
            )


def init_moe_params(
    key: jax.Array, embed_dim: int, mlp_dim: int, config: MoEConfig,
    param_dtype=jnp.float32, num_layers: Optional[int] = None,
) -> Dict[str, jax.Array]:
    """Per-layer expert weights; with num_layers, adds a leading stacked dim."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    lead = () if num_layers is None else (num_layers,)
    E = config.num_experts

    def normal(key, shape, s=0.02):
        return (jax.random.normal(key, shape) * s).astype(param_dtype)

    params = {
        "router_w": normal(k1, lead + (embed_dim, E)),
        "expert_fc": normal(k2, lead + (E, embed_dim, mlp_dim)),
        "expert_out": normal(k3, lead + (E, mlp_dim, embed_dim)),
    }
    if config.activation == "swiglu":
        # Mixtral-style gated experts: fc is the "up" proj, gate multiplies
        params["expert_gate"] = normal(k4, lead + (E, embed_dim, mlp_dim))
    return params


def moe_param_axes(num_layers: Optional[int] = None,
                   config: Optional[MoEConfig] = None) -> Dict[str, tuple]:
    lead = () if num_layers is None else ("stage",)
    axes = {
        "router_w": lead + ("embed", None),
        "expert_fc": lead + ("expert", "embed", "mlp"),
        "expert_out": lead + ("expert", "mlp", "embed"),
    }
    if config is not None and config.activation == "swiglu":
        axes["expert_gate"] = lead + ("expert", "embed", "mlp")
    return axes


def _top_k_mask(probs: jax.Array, k: int) -> jax.Array:
    """[T, E] probs → 0/1 mask of the top-k experts per token."""
    _, idx = jax.lax.top_k(probs, k)
    return jax.nn.one_hot(idx, probs.shape[-1], dtype=probs.dtype).sum(axis=1)


def moe_layer(
    params: Dict[str, jax.Array],
    x: jax.Array,
    config: MoEConfig,
    *,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """x: [B, T, D] → (out [B, T, D], aux_loss scalar)."""
    B, T, D = x.shape
    E = config.num_experts
    tokens = x.reshape(B * T, D)
    n_tok = B * T
    capacity = max(
        int(n_tok * config.top_k * config.capacity_factor / E), config.top_k
    )

    router_logits = jnp.einsum(
        "td,de->te", tokens.astype(jnp.float32),
        params["router_w"].astype(jnp.float32),
    )
    if config.router_jitter and rng is not None:
        router_logits += config.router_jitter * jax.random.normal(
            rng, router_logits.shape
        )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [T, E]
    topk_mask = _top_k_mask(probs, config.top_k)    # [T, E] 0/1

    if config.dropless:
        # Per-token routing with no cross-token capacity interaction: the
        # dense-all-experts formulation (every expert runs on every token,
        # combine masks to top-k). FLOP cost is E/k of the capacity path —
        # the right trade at decode batch sizes; with "expert" sharded the
        # combine contraction psums over the expert axis.
        gates = probs * topk_mask
        gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
        h = jnp.einsum("td,edm->tem", tokens,
                       params["expert_fc"].astype(x.dtype))
        if config.activation == "swiglu":
            g = jnp.einsum("td,edm->tem", tokens,
                           params["expert_gate"].astype(x.dtype))
            h = jax.nn.silu(g) * h
        else:
            h = jax.nn.gelu(h)
        y = jnp.einsum("tem,emd->ted", h,
                       params["expert_out"].astype(x.dtype))
        out = jnp.einsum("te,ted->td", gates.astype(x.dtype), y)
        me = probs.mean(axis=0)
        ce = topk_mask.mean(axis=0) / config.top_k
        aux = config.aux_loss_weight * E * jnp.sum(me * ce)
        return out.reshape(B, T, D), aux

    # Position of each token within its expert's queue; drop overflow.
    pos = jnp.cumsum(topk_mask, axis=0) * topk_mask          # [T, E] 1-based
    keep = (pos > 0) & (pos <= capacity)
    pos = (pos - 1).astype(jnp.int32)

    gates = probs * topk_mask * keep                        # [T, E]
    denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    gates = gates / denom

    # dispatch [T, E, C]: one-hot over capacity slots
    dispatch = keep[..., None] * jax.nn.one_hot(pos, capacity, dtype=x.dtype)
    combine = gates[..., None].astype(jnp.float32) * dispatch

    # These einsums become all_to_all when "expert" is a sharded mesh axis.
    expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)  # [E, C, D]
    h = jnp.einsum("ecd,edm->ecm", expert_in,
                   params["expert_fc"].astype(x.dtype))
    if config.activation == "swiglu":
        gate = jnp.einsum("ecd,edm->ecm", expert_in,
                          params["expert_gate"].astype(x.dtype))
        h = jax.nn.silu(gate) * h
    else:
        h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecm,emd->ecd", h,
                            params["expert_out"].astype(x.dtype))
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)

    # Load-balancing auxiliary loss (Switch §2.2): mean gate fraction ×
    # token fraction per expert, scaled by E.
    me = probs.mean(axis=0)
    ce = topk_mask.mean(axis=0) / config.top_k
    aux = config.aux_loss_weight * E * jnp.sum(me * ce)
    return out.reshape(B, T, D), aux
