"""Device mesh construction: the TPU-native resource model for parallelism.

This replaces the reference's delegation of TP/PP/EP to engine kwargs
(reference: ``python/ray/llm/_internal/serve/engines/vllm/vllm_models.py:231``
reads tensor/pipeline_parallel_size and hands them to vLLM; SURVEY.md §2.3
notes SP/CP are absent entirely). Here every parallelism strategy is a named
mesh axis; XLA inserts the collectives (psum/all_gather/reduce_scatter/
ppermute) over ICI according to shardings.

Axes (any may be 1):
    data   — data parallelism (gradient psum)
    fsdp   — parameter/optimizer sharding a la ZeRO-3 (all_gather on use)
    tensor — tensor/model parallelism (Megatron-style column/row splits)
    seq    — sequence/context parallelism (ring attention over ICI ring)
    expert — MoE expert parallelism (all_to_all routing)
    stage  — pipeline stages (microbatch loop with ppermute handoff)
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "stage", "tensor", "seq", "expert")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative parallelism layout. -1 on exactly one axis = "fill with
    remaining devices" (like a reshape wildcard)."""

    data: int = -1
    fsdp: int = 1
    stage: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {
            "data": self.data,
            "fsdp": self.fsdp,
            "stage": self.stage,
            "tensor": self.tensor,
            "seq": self.seq,
            "expert": self.expert,
        }
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"only one wildcard axis allowed, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but {n_devices} available"
            )
        return sizes

    def build(self, devices: Optional[List] = None) -> Mesh:
        return make_mesh(self, devices)


def make_mesh(config: MeshConfig, devices: Optional[List] = None) -> Mesh:
    """Build a jax Mesh laid out so the innermost axes (tensor/seq/expert —
    the chattiest collectives) map to adjacent devices: on a real slice those
    are ICI neighbors (same recipe as jax.experimental.mesh_utils; on v4/v5p
    3D tori jax's create_device_mesh does the topology-aware assignment)."""
    if devices is None:
        devices = jax.devices()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    return make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def mesh_shape(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


@dataclass(frozen=True)
class TpuSliceSpec:
    """Typed TPU slice description — a first-class scheduler concept (the
    reference encodes this as string resources + labels from GCE metadata,
    ``python/ray/_private/accelerators/tpu.py:475-588``; we promote it to a
    typed object as SURVEY.md §7 prescribes)."""

    generation: str = "v5e"        # v4 | v5e | v5p | v6e ...
    topology: Tuple[int, ...] = (2, 2)   # chip grid, e.g. (4, 4) = v5e-16
    hosts: int = 1
    chips_per_host: int = 4

    @property
    def num_chips(self) -> int:
        return int(math.prod(self.topology))

    @property
    def name(self) -> str:
        return f"{self.generation}-{self.num_chips}"

    def head_resource(self) -> str:
        """Resource name the scheduler uses to reserve a whole ICI slice
        (semantics of the reference's TPU-{pod}-head resource,
        ``tpu.py:634``)."""
        return f"TPU-{self.name}-head"


def detect_local_tpu() -> Optional[TpuSliceSpec]:
    """Best-effort description of locally attached TPU chips."""
    try:
        tpus = [d for d in jax.devices() if d.platform == "tpu"]
    except Exception:
        return None
    if not tpus:
        return None
    n = len(tpus)
    kind = getattr(tpus[0], "device_kind", "tpu")
    gen = "v5e"
    for tag in ("v6e", "v5p", "v5e", "v5", "v4", "v3", "v2"):
        if tag in str(kind).lower().replace(" ", ""):
            gen = tag
            break
    return TpuSliceSpec(generation=gen, topology=(n,), hosts=1, chips_per_host=n)
