"""Logical-axis sharding rules: name tensor dims, map them to mesh axes.

The scaling-book recipe: pick a mesh, annotate arrays with logical axis names,
resolve names → mesh axes through one rules table, let XLA insert collectives.
(The reference has no analog — its data plane is NCCL calls; SURVEY.md §5.)
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated)
Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Default rules for transformer training. Conventions:
#   batch    -> data (+ fsdp when both shard the batch dimension of activations)
#   embed    -> fsdp for params (ZeRO-3 gather-on-use)
#   mlp/heads/kv -> tensor (Megatron column/row splits)
#   seq      -> seq axis (context parallelism / ring attention)
#   expert   -> expert
#   stage    -> stage (stacked pipeline bodies)
DEFAULT_RULES: Rules = {
    "batch": ("data", "fsdp"),
    "seq": "seq",
    "embed": "fsdp",
    "mlp": "tensor",
    "heads": "tensor",
    "kv": None,
    "head_dim": None,
    "vocab": "tensor",
    "expert": "expert",
    "stage": "stage",
    "norm": None,
}


def spec_from_logical(
    logical_axes: Sequence[Optional[str]], rules: Optional[Rules] = None
) -> P:
    rules = {**DEFAULT_RULES, **(rules or {})}
    parts = []
    used = set()
    for name in logical_axes:
        if name is None:
            parts.append(None)
            continue
        mesh_axes = rules.get(name)
        if mesh_axes is None:
            parts.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        # A mesh axis may appear only once in a PartitionSpec.
        mesh_axes = tuple(a for a in mesh_axes if a not in used)
        used.update(mesh_axes)
        if not mesh_axes:
            parts.append(None)
        elif len(mesh_axes) == 1:
            parts.append(mesh_axes[0])
        else:
            parts.append(mesh_axes)
    return P(*parts)


def named_sharding(
    mesh: Mesh, logical_axes: Sequence[Optional[str]], rules: Optional[Rules] = None
) -> NamedSharding:
    return NamedSharding(mesh, spec_from_logical(logical_axes, rules))


def tree_shardings(mesh: Mesh, logical_tree, rules: Optional[Rules] = None):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )


def shard_batch_spec(mesh: Mesh, rules: Optional[Rules] = None) -> NamedSharding:
    """Sharding for (batch, seq) token arrays."""
    return named_sharding(mesh, ("batch", "seq"), rules)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def with_sharding_constraint(x, mesh: Mesh, logical_axes, rules=None):
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, logical_axes, rules)
    )
