"""Same-host RPC transport over the native shm ring (native/src/ring.cc).

``RingConnection`` presents the same call/notify surface as
``protocol.Connection`` but rides the futex-doorbell shared-memory ring
instead of asyncio TCP: a request is one encode + one ring send from the
caller's thread, and the receiving side drains whole batches per wakeup on a
dedicated pump thread — the hot task path never touches either process's
event-loop socket machinery.

Reference shape (behavior, not code): the C++ core worker's in-process
submit/reply plane — ``src/ray/core_worker/core_worker.h:167`` and
``task_submission/normal_task_submitter.h:86`` run task submission on native
threads; Python is only entered to execute the user function. Here the
native layer is the transport + wakeup; header decode stays msgpack for
wire-format parity with the TCP plane (msgpack is C-speed).

Fast-path dispatch: the owning CoreWorker may register a ``fast_dispatch``
callback, tried on the pump thread for each incoming request; returning True
means the request was fully handled off-loop (e.g. a cached-function task
executed straight on the task executor, reply sent from that thread).
Everything else is forwarded to the asyncio handler, preserving slow-path
semantics exactly.
"""
from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from ray_tpu._private import faultpoints, flight, protocol
from ray_tpu._private.asyncio_util import spawn_logged
from ray_tpu.native.ring import (
    NativeRing,
    RingClosed,
    RingFull,
    RingMessageTooBig,
)

logger = logging.getLogger(__name__)

# One send may block briefly while the peer drains a full ring; beyond this
# the peer is considered wedged and the connection is torn down.
SEND_TIMEOUT_MS = 30_000

# Loop-side sends queue here while the ring is full. A peer that stops
# draining (but keeps its TCP conn up) would otherwise grow this without
# bound while the drainer moves one message per SEND_TIMEOUT_MS — cap the
# backlog at a few ring capacities (derived per-connection from the ring's
# geometry so one max-size message always fits) and treat overflow like a
# wedged peer.
BACKLOG_RING_CAPACITIES = 4


class MessageTooBig(protocol.RpcError):
    """Payload exceeds the ring; caller should retry over TCP. NOT fatal to
    the connection."""


class RingConnection:
    """One endpoint of a bidirectional shm-ring RPC channel.

    Mirrors ``protocol.Connection``: either side may issue requests; replies
    are matched by correlation id. ``call`` must run on the event loop;
    ``notify``/``send_reply`` may run on any thread (the ring binding
    serializes senders).
    """

    def __init__(
        self,
        ring: NativeRing,
        loop: asyncio.AbstractEventLoop,
        handler=None,
        fast_dispatch: Optional[Callable] = None,
        fast_batch: Optional[Callable] = None,
        name: str = "",
    ):
        self.ring = ring
        self.loop = loop
        self.handler = handler
        self.fast_dispatch = fast_dispatch
        # Optional whole-batch fast path: receives every sub-request of one
        # "batch" wire message at once (list of (header, frames)) and
        # returns the leftovers for the slow path. Lets the executor side
        # run a burst as a few grouped submissions with ONE batched reply
        # per group instead of per-task submit/encode/send.
        self.fast_batch = fast_batch
        self.name = name or ring.name
        self.peer_info: dict = {}
        self.on_close: Optional[Callable] = None
        self._ids = itertools.count(1)
        self._pending = {}
        self._plock = threading.Lock()
        self._closed = False
        # Loop-thread sends never futex-block: when the ring is full the
        # encoded message joins this FIFO backlog and a drainer task pushes
        # it from an executor thread (order preserved; the loop stays live).
        self._backlog: List[bytes] = []
        self._backlog_bytes = 0
        # max_msg is half the ring capacity; cap ≈ 4 capacities.
        self._backlog_max = BACKLOG_RING_CAPACITIES * 2 * ring.max_msg
        self._drainer_running = False
        # Round 16: drain-wide batch handoff — every request of one pump
        # drain goes to fast_batch in ONE pass (one corr-claim pass,
        # O(task slots) executor wakeups per drain, not per message).
        # Gate read once; config import is deferred like protocol's.
        from ray_tpu._private.config import rt_config

        self._batch_drain = bool(rt_config.pump_batch_drain)
        # Pump economics (bench/tests): drains, messages, and a
        # power-of-2 histogram of messages-per-drain. Written by the
        # pump thread only; readers snapshot.
        self.pump_stats: dict = {"drains": 0, "msgs": 0, "batch_hist": {}}
        # Driver-side settle economics: reply frames applied per loop
        # wakeup (the ring analog of Connection.settle_stats).
        self.settle_stats: dict = {
            "wakeups": 0, "frames": 0, "drained": 0, "max_batch": 0,
        }
        # Round 20: the driver attaches its SettlePlane here as the
        # settle-discipline switch. The pump thread never queues to the
        # plane (it is itself off-loop already); attachment means the
        # pump prepares each drain's replies in place — pops + per-loop
        # bucketing — and stamps the handoff for settle-dwell
        # attribution.
        self.settle_plane = None
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True,
            name=f"rt-ringpump-{self.name}",
        )
        self._pump.start()

    @property
    def max_msg(self) -> int:
        return self.ring.max_msg

    # ------------------------------------------------------------- sending

    def _send(self, header: dict, frames: List[bytes]):
        """Blocking send — call from non-loop threads (executor replies)."""
        data = protocol.encode_message(header, list(frames))
        if len(data) > self.ring.max_msg:
            raise MessageTooBig(
                f"{len(data)}B exceeds ring {self.name} capacity"
            )
        try:
            self.ring.send(data, timeout_ms=SEND_TIMEOUT_MS)
        except RingMessageTooBig:
            raise MessageTooBig(f"ring {self.name}: message too big")
        except RingFull:
            self._teardown()  # peer wedged for SEND_TIMEOUT_MS
            raise protocol.ConnectionLost(f"ring {self.name}: peer wedged")
        except (RingClosed, OSError) as e:
            self._teardown()
            raise protocol.ConnectionLost(
                f"ring {self.name}: {e}"
            ) from None

    def _send_from_loop(self, header: dict, frames: List[bytes]):
        """Ordered non-blocking send for the event-loop thread: try a
        zero-timeout push; when full, append to the backlog drained by an
        executor thread."""
        data = protocol.encode_message(header, list(frames))
        if len(data) > self.ring.max_msg:
            raise MessageTooBig(
                f"{len(data)}B exceeds ring {self.name} capacity"
            )
        if self._closed:
            raise protocol.ConnectionLost(f"ring {self.name} closed")
        if not self._backlog:
            try:
                self.ring.send(data, timeout_ms=0)
                return
            except RingFull:
                pass
            except RingMessageTooBig:
                raise MessageTooBig(f"ring {self.name}: message too big")
            except (RingClosed, OSError) as e:
                self._teardown()
                raise protocol.ConnectionLost(
                    f"ring {self.name}: {e}"
                ) from None
        if self._backlog_bytes + len(data) > self._backlog_max:
            self._teardown()
            raise protocol.ConnectionLost(
                f"ring {self.name}: peer not draining "
                f"({self._backlog_bytes}B backlogged)"
            )
        self._backlog.append(data)
        self._backlog_bytes += len(data)
        if not self._drainer_running:
            self._drainer_running = True
            spawn_logged(self.loop, self._drain_backlog(),
                         "ring.drain_backlog")

    async def _drain_backlog(self):
        try:
            while self._backlog and not self._closed:
                data = self._backlog[0]

                def push(d=data):
                    self.ring.send(d, timeout_ms=SEND_TIMEOUT_MS)

                try:
                    await self.loop.run_in_executor(None, push)
                except (RingClosed, RingFull, OSError):
                    self._teardown()
                    return
                self._backlog.pop(0)
                self._backlog_bytes -= len(data)
        finally:
            self._drainer_running = False

    def _send_auto(self, header: dict, frames):
        """Route to the non-blocking loop path or the blocking thread path
        depending on the calling thread."""
        fl = flight.ENABLED
        if fl:
            fl_t0 = time.monotonic()
            # No fallback to the per-connection integer id: those collide
            # across connections and would fabricate cross-process joins.
            fl_cid = header.get("corr") or header.get("fid")
            fl_bytes = sum(len(f) for f in frames)
        if faultpoints.ACTIVE:
            # drop: the message silently never enters the ring; error
            # surfaces as the transport failure callers already handle.
            if faultpoints.fire(
                "ring.push", err=protocol.ConnectionLost
            ) == "drop":
                if fl:
                    # record() picks up the fault stamp note_fault just set
                    flight.record("ring.push", fl_cid, "ring", fl_t0,
                                  time.monotonic(), fl_bytes, "ok")
                return
        try:
            on_loop = asyncio.get_running_loop() is self.loop
        except RuntimeError:
            on_loop = False
        try:
            if on_loop:
                self._send_from_loop(header, list(frames))
            else:
                self._send(header, list(frames))
        except (protocol.RpcError, OSError) as e:
            if fl:
                flight.record("ring.push", fl_cid, "ring", fl_t0,
                              time.monotonic(), fl_bytes,
                              f"error:{type(e).__name__}")
            raise
        if fl:
            flight.record("ring.push", fl_cid, "ring", fl_t0,
                          time.monotonic(), fl_bytes, "ok")

    async def call(
        self, method: str, extras: Optional[dict] = None, frames=()
    ) -> Tuple[dict, List[bytes]]:
        if self._closed:
            raise protocol.ConnectionLost(f"ring {self.name} closed")
        cid = next(self._ids)
        header = {"i": cid, "m": method}
        if extras:
            header.update(extras)
        if flight.ENABLED and "corr" not in header and "fid" not in header:
            header["fid"] = flight.next_id()
        # The future homes on the CALLING loop (round 20: sharded pusher
        # loops await ring calls from their own threads; reply settling
        # routes by fut.get_loop()). On the main loop this is exactly
        # self.loop.create_future().
        fut = asyncio.get_running_loop().create_future()
        with self._plock:
            self._pending[cid] = fut
        try:
            self._send_auto(header, frames)
        except (protocol.ConnectionLost, MessageTooBig):
            with self._plock:
                self._pending.pop(cid, None)
            raise
        try:
            return await fut
        finally:
            # A deadline-bounded caller (wait_for) cancelling this wait
            # must not leave a dead pending entry until teardown.
            with self._plock:
                self._pending.pop(cid, None)

    def notify(self, method: str, extras: Optional[dict] = None, frames=()):
        header = {"i": next(self._ids), "m": method, "oneway": 1}
        if extras:
            header.update(extras)
        self._send_auto(header, frames)

    def call_batch(self, method: str, items) -> list:
        """Issue many requests in ONE ring message (must run on an event
        loop thread — the driver's main loop, or a round-20 pusher shard
        whose loop then owns the returned futures).

        ``items``: [(extras, frames)]. Returns one future per item; the
        receiver replies to each sub-request individually under its own
        correlation id, so failures and results resolve per item. This is
        the wire analog of pipelined task submission: a burst of small
        pushes costs one encode + one send + one peer wakeup.
        """
        if self._closed:
            raise protocol.ConnectionLost(f"ring {self.name} closed")
        try:
            floop = asyncio.get_running_loop()
        except RuntimeError:
            floop = self.loop
        futs = []
        subs = []
        counts = []
        all_frames: List[bytes] = []
        fl = flight.ENABLED
        with self._plock:
            for extras, frames in items:
                cid = next(self._ids)
                fut = floop.create_future()
                self._pending[cid] = fut
                futs.append(fut)
                sub = {"i": cid, **(extras or {})}
                if fl and "corr" not in sub and "fid" not in sub:
                    sub["fid"] = flight.next_id()
                subs.append(sub)
                counts.append(len(frames))
                all_frames.extend(frames)
        header = {
            "i": next(self._ids), "m": "batch", "oneway": 1,
            "bm": method, "bh": subs, "bn": counts,
        }
        try:
            self._send_auto(header, all_frames)
        except (protocol.ConnectionLost, MessageTooBig):
            with self._plock:
                for sub in subs:
                    self._pending.pop(sub["i"], None)
            raise
        return futs

    def send_reply_batch(self, subs: List[dict], counts: List[int],
                         frames: List[bytes],
                         extras: Optional[dict] = None):
        """Reply to many requests in ONE ring message (any thread).

        ``subs[k]`` must carry its request's correlation id under ``i``;
        ``counts[k]`` frames belong to it. ``extras`` merges into the
        batch header (e.g. the reply window's ``wa`` ack request). When
        the combined message exceeds the ring, each sub-reply is sent
        individually (whose own too-big handling degrades to an inline
        error) — a batch that cannot be correlated must never leave
        sub-futures hanging."""
        header = {"r": 1, "bh": subs, "bn": counts}
        if extras:
            header.update(extras)
        try:
            self._send_auto(header, frames)
            return
        except MessageTooBig:
            pass
        except protocol.ConnectionLost:
            return
        pos = 0
        for sub, n in zip(subs, counts):
            self.send_reply({**sub, "r": 1}, frames[pos:pos + n])
            pos += n

    def send_reply(self, header: dict, frames: List[bytes]):
        """Reply to a request (any thread)."""
        try:
            self._send_auto(header, frames)
        except protocol.ConnectionLost as e:
            # Peer gone; its pending future fails via teardown there.
            logger.debug("ring reply seq=%s dropped, peer gone: %s",
                         header.get("seq"), e)
        except MessageTooBig:
            # Reply exceeds the ring: deliver an error instead so the caller
            # fails fast rather than timing out (large results normally ride
            # shm metas, not inline frames).
            try:
                self._send_auto(
                    {
                        "i": header.get("i"), "r": 1,
                        "e": "reply too large for ring transport",
                    },
                    [],
                )
            except Exception:
                pass

    # ----------------------------------------------------------- receiving

    def _pump_loop(self):
        try:
            while not self._closed:
                try:
                    msgs = self.ring.recv_many(500)
                except RingClosed:
                    break
                except OSError as e:
                    logger.debug("ring %s recv error: %s", self.name, e)
                    break
                if not msgs:
                    continue
                st = self.pump_stats
                st["drains"] += 1
                st["msgs"] += len(msgs)
                b = 1
                while b < len(msgs):
                    b <<= 1
                st["batch_hist"][b] = st["batch_hist"].get(b, 0) + 1
                replies = []
                slow = []
                reqs = []  # drain-wide batch handoff (gate on)
                fast = self.fast_dispatch
                batch_drain = (
                    self._batch_drain and self.fast_batch is not None
                )
                for m in msgs:
                    if faultpoints.ACTIVE:
                        try:
                            if faultpoints.fire(
                                "ring.pop", err=OSError
                            ) == "drop":
                                continue  # this message is lost in transit
                        except OSError as e:
                            logger.debug(
                                "ring %s: injected recv failure: %s",
                                self.name, e,
                            )
                            return  # finally: _teardown (ring wedged)
                    try:
                        header, frames = protocol.decode_message_bytes(m)
                    except Exception:
                        logger.exception("ring %s: undecodable message",
                                         self.name)
                        continue
                    if flight.ENABLED:
                        t_pop = time.monotonic()
                        header["_fr"] = t_pop
                        flight.record(
                            "ring.pop",
                            header.get("corr") or header.get("fid"),
                            "ring", t_pop, t_pop, len(m),
                            "reply" if header.get("r")
                            else str(header.get("m")),
                        )
                    elif header.get("r"):
                        # Reply arrival stamps are ALWAYS on: the
                        # driver's push windows clock their AIMD on
                        # push->arrival latency (driver-side settle
                        # queueing excluded — it is not executor
                        # congestion). One monotonic + dict store per
                        # reply message.
                        header["_fr"] = time.monotonic()
                    if header.get("r"):
                        if "bh" in header:
                            # Batched reply: sub-replies ride one message,
                            # each under its own correlation id. The
                            # arrival stamp rides onto every sub so the
                            # driver can carve its settle dwell into the
                            # pump-queue phase.
                            pos = 0
                            fr_t = header.get("_fr")
                            for sub, n in zip(header["bh"], header["bn"]):
                                if fr_t is not None:
                                    sub["_fr"] = fr_t
                                replies.append((sub, frames[pos:pos + n]))
                                pos += n
                            if header.get("wa"):
                                # Ack the sender's reply window so the
                                # results that completed behind this
                                # frame flush as the next one.
                                try:
                                    self._send_auto(
                                        {"i": next(self._ids),
                                         "m": "mrack", "oneway": 1}, [],
                                    )
                                except (protocol.RpcError, OSError) as e:
                                    logger.debug(
                                        "ring %s: window ack dropped: %s",
                                        self.name, e,
                                    )
                        else:
                            replies.append((header, frames))
                        continue
                    if header.get("m") == "batch":
                        # Unpack sub-requests: each carries its own id and
                        # resolves (fast or slow) independently.
                        method = header.get("bm")
                        pos = 0
                        subs = []
                        for sub, n in zip(header["bh"], header["bn"]):
                            sub["m"] = method
                            if flight.ENABLED:
                                sub["_fr"] = header.get("_fr")
                            subs.append((sub, frames[pos:pos + n]))
                            pos += n
                        if batch_drain:
                            # Joined to the drain-wide handoff below:
                            # sub-requests of EVERY batch message in this
                            # drain share one claim pass + work queue.
                            reqs.extend(subs)
                            continue
                        if self.fast_batch is not None:
                            try:
                                subs = self.fast_batch(subs, self)
                            except Exception:
                                logger.exception(
                                    "ring batch fast dispatch failed; slow"
                                )
                        for sub, sfr in subs:
                            if fast is not None:
                                try:
                                    if fast(sub, sfr, self):
                                        continue
                                except Exception:
                                    logger.exception(
                                        "ring fast dispatch failed; slow"
                                    )
                            slow.append((sub, sfr))
                        continue
                    if batch_drain:
                        # Plain requests ride the same drain-wide handoff
                        # (arrival order preserved: per-caller actor seq
                        # admission sees them exactly as the per-message
                        # path would).
                        reqs.append((header, frames))
                        continue
                    if fast is not None:
                        try:
                            if fast(header, frames, self):
                                continue
                        except Exception:
                            logger.exception(
                                "ring fast dispatch failed; slow path"
                            )
                    slow.append((header, frames))
                if reqs:
                    # ONE batch handoff covering every request of this
                    # drain; leftovers keep per-item fast/slow semantics.
                    try:
                        leftovers = self.fast_batch(reqs, self)
                    except Exception:
                        logger.exception(
                            "ring drain batch dispatch failed; slow"
                        )
                        leftovers = reqs
                    for sub, sfr in leftovers:
                        if fast is not None:
                            try:
                                if fast(sub, sfr, self):
                                    continue
                            except Exception:
                                logger.exception(
                                    "ring fast dispatch failed; slow path"
                                )
                        slow.append((sub, sfr))
                if replies:
                    if self.settle_plane is not None:
                        # Round 20: this pump thread IS the ring's
                        # settle plane — it already runs off the event
                        # loop, so queueing the drain to the driver's
                        # plane THREAD would only insert a second,
                        # GIL-starved hop on the reply path (measured on
                        # the 1-core A/B box: 616ms median reply dwell
                        # through the queued plane vs 145ms settling
                        # from here). Prepare in place — pop futures,
                        # bucket by owning loop — and re-enter each loop
                        # once per drain. The handoff stamp lands first:
                        # the driver carves arrival->handoff into
                        # pump-queue and handoff->settle into
                        # settle-dwell.
                        t_sq = time.monotonic()
                        for h, _f in replies:
                            h["_sq"] = t_sq
                        for floop, fn, ops in self._settle_prepare(
                                replies):
                            try:
                                floop.call_soon_threadsafe(fn, ops)
                            except RuntimeError:
                                # That loop already closed (shutdown):
                                # its futures were failed by teardown.
                                pass
                        replies = []
                if replies or slow:
                    # One loop wakeup per drained batch, covering both reply
                    # resolution and slow-path request dispatch.
                    try:
                        self.loop.call_soon_threadsafe(
                            self._apply_batch, replies, slow
                        )
                    except RuntimeError:
                        break  # loop closed
        finally:
            self._teardown()

    def _apply_batch(self, replies, slow):
        if replies:
            st = self.settle_stats
            st["wakeups"] += 1
            st["frames"] += len(replies)
            if len(replies) > 1:
                st["drained"] += len(replies) - 1
            if len(replies) > st["max_batch"]:
                st["max_batch"] = len(replies)
        self._apply_replies(replies)
        for header, frames in slow:
            spawn_logged(self.loop, self._handle_slow(header, frames),
                         "ring.handle_slow")

    async def _handle_slow(self, header: dict, frames: List[bytes]):
        reply = {"i": header["i"], "r": 1}
        fl = flight.ENABLED
        if fl:
            t_arr = header.get("_fr") or time.monotonic()
            t_run = time.monotonic()
            fl_verb = f"rpc.s.{header.get('m')}"
            fl_out = "ok"
        try:
            extras, rframes = await self.handler(
                header["m"], header, frames, self
            )
            if extras is protocol.REPLY_HANDLED:
                # Result routed into a coalesced reply frame (worker
                # reply window); the window answers this correlation id.
                if fl:
                    flight.record_dispatch(fl_verb, "server", header,
                                           t_arr, t_run, 0, "windowed")
                return
            if extras:
                reply.update(extras)
        except faultpoints.DropReply:
            if fl:
                flight.record_dispatch(fl_verb, "server", header, t_arr,
                                       t_run, 0, "drop_reply")
            return  # injected: verb applied, reply swallowed
        except Exception as e:
            reply["e"] = f"{type(e).__name__}: {e}"
            code = getattr(e, "code", None)
            if code is not None:
                reply["ec"] = code
            rframes = []
            if fl:
                fl_out = f"error:{type(e).__name__}"
        if fl:
            flight.record_dispatch(
                fl_verb, "server", header, t_arr, t_run,
                sum(len(f) for f in rframes), fl_out,
            )
        if header.get("oneway"):
            return
        self.send_reply(reply, rframes)

    def _apply_replies(self, replies):
        forwarded = None
        for header, frames in replies:
            with self._plock:
                fut = self._pending.pop(header.get("i"), None)
            if fut is None or fut.done():
                continue
            try:
                floop = fut.get_loop()
            except Exception:
                floop = self.loop
            if floop is not self.loop:
                # Round 20: a future homed on a pusher-shard loop (the
                # settle plane normally routes these, but the plane may
                # be off or full while shards are on). Group and forward
                # — settling a foreign loop's future inline would race
                # its callbacks.
                if forwarded is None:
                    forwarded = {}
                forwarded.setdefault(floop, []).append(
                    self._reply_op(fut, header, frames))
                continue
            if header.get("e") is not None:
                fut.set_exception(
                    protocol.RpcError(header["e"], code=header.get("ec"))
                )
            else:
                fut.set_result((header, frames))
        if forwarded:
            for floop, ops in forwarded.items():
                try:
                    floop.call_soon_threadsafe(
                        self._settle_ops_on_loop, ops)
                except RuntimeError:
                    pass  # shard loop closed at shutdown

    @staticmethod
    def _reply_op(fut, header, frames):
        """(fut, value, is_error) op consumed by _settle_ops_on_loop."""
        if header.get("e") is not None:
            return (fut,
                    protocol.RpcError(header["e"], code=header.get("ec")),
                    True)
        return (fut, (header, frames), False)

    # ----------------------------------------------- round-20 settle plane
    def _settle_prepare(self, replies):
        """SettlePlane contract, PLANE-THREAD side: pop this drain's
        futures under the pending lock and bucket ready-to-apply ops by
        each future's owning loop — the plane then re-enters every loop
        once per drain. Stats stay single-writer (this runs only on the
        plane thread)."""
        st = self.settle_stats
        st["wakeups"] += 1
        st["frames"] += len(replies)
        if len(replies) > 1:
            st["drained"] += len(replies) - 1
        if len(replies) > st["max_batch"]:
            st["max_batch"] = len(replies)
        with self._plock:
            pend = self._pending
            popped = [(pend.pop(h.get("i"), None), h, fr)
                      for h, fr in replies]
        buckets = {}
        for fut, h, fr in popped:
            if fut is None:
                continue
            try:
                floop = fut.get_loop()
            except Exception:
                floop = self.loop
            buckets.setdefault(floop, []).append(self._reply_op(fut, h, fr))
        return [(floop, self._settle_ops_on_loop, ops)
                for floop, ops in buckets.items()]

    def _settle_ops_on_loop(self, ops):
        """Apply prepared (fut, value, is_error) ops on the loop that
        owns the futures. A future cancelled while its reply was in
        flight (deadline re-arm) is simply skipped."""
        for fut, val, is_err in ops:
            if fut.done():
                continue
            if is_err:
                fut.set_exception(val)
            else:
                fut.set_result(val)

    # ------------------------------------------------------------ teardown

    def _teardown(self):
        if self._closed:
            return
        self._closed = True
        self.ring.close()
        # Drop the /dev/shm name now (creator side): mappings keep the
        # segment alive for any in-flight reader, but a closed connection
        # must not leak 8MB of tmpfs per ring until reboot.
        try:
            self.ring.unlink_name()
        except Exception:
            pass
        with self._plock:
            pending, self._pending = dict(self._pending), {}

        if pending:
            # Group by owning loop (round 20: pusher-shard futures), one
            # scheduled failure pass per loop. Single-loop topology keeps
            # the pre-round-20 one-callback shape.
            buckets: dict = {}
            for fut in pending.values():
                try:
                    floop = fut.get_loop()
                except Exception:
                    floop = self.loop
                buckets.setdefault(floop, []).append(fut)
            for floop, futs in buckets.items():

                def fail_all(futs=futs):
                    for fut in futs:
                        if not fut.done():
                            fut.set_exception(
                                protocol.ConnectionLost(
                                    f"ring {self.name} lost")
                            )

                try:
                    floop.call_soon_threadsafe(fail_all)
                except RuntimeError:
                    pass
        if self.on_close is not None:
            try:
                self.on_close(self)
            except Exception:
                logger.exception("ring on_close failed")

    async def close(self):
        self._teardown()

    def detach(self):
        """Final cleanup after the pump exited: unmap the segment."""
        self._teardown()
        if self._pump.is_alive():
            self._pump.join(timeout=2)
        self.ring.detach()
