"""Usage stats: opt-out, local-record-only.

Reference analog: ``python/ray/_private/usage`` + ``usage_stats_client.cc``
(opt-out usage pings). This environment has no egress, so the equivalent
records a single local JSON blob per session under the session temp dir —
the collection/opt-out shape is preserved (RAY_TPU_USAGE_STATS_ENABLED=0
disables), the reporting sink is a file instead of a service.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def record_session_start(session_dir: Optional[str] = None,
                         extra: Optional[dict] = None) -> Optional[str]:
    """Write the session's usage record; returns the path or None when
    disabled/unwritable. Never raises — telemetry must not break startup."""
    if not usage_stats_enabled():
        return None
    try:
        # per-uid dir (multi-user hosts must not collide on a shared /tmp
        # path) and a timestamped name (PID reuse must not overwrite a
        # prior session's record)
        uid = os.getuid() if hasattr(os, "getuid") else 0
        d = session_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"ray_tpu_{uid}"
        )
        os.makedirs(d, mode=0o700, exist_ok=True)
        # /tmp is shared: never write into a directory another user (or a
        # symlink planter) controls
        st = os.lstat(d)
        import stat as _stat

        if not _stat.S_ISDIR(st.st_mode) or st.st_uid != uid:
            return None
        payload = {
            "schema_version": 1,
            "timestamp": time.time(),
            "python": sys.version.split()[0],
            "platform": sys.platform,
            "num_cpus": os.cpu_count(),
            **(extra or {}),
        }
        path = os.path.join(
            d, f"usage_stats_{int(time.time() * 1000)}_{os.getpid()}.json"
        )
        with open(path, "w") as f:
            json.dump(payload, f)
        return path
    except Exception:
        return None
