"""Cached virtualenv creation for runtime_env pip/uv plugins.

Reference analog: ``python/ray/_private/runtime_env/pip.py`` / ``uv.py`` —
one venv per unique requirement set, content-hash keyed, created once per
machine and reused (deleting/rebuilding per task would dwarf task runtimes).
Creation is serialized by an exclusive file lock so N workers racing on the
same env build it once.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import subprocess
import sys
import tempfile
from typing import List, Optional

logger = logging.getLogger(__name__)


def _env_root() -> str:
    return os.environ.get("RT_RUNTIME_ENV_DIR") or os.path.join(
        tempfile.gettempdir(), f"rt_runtime_env_{os.getuid()}"
    )


def env_key(packages: List[str], use_uv: bool) -> str:
    blob = json.dumps(
        {"pkgs": sorted(packages), "uv": use_uv,
         "py": sys.version_info[:2]},
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def ensure_venv(packages: List[str], use_uv: bool = False,
                timeout: float = 600.0) -> str:
    """Create (or reuse) a venv with ``packages`` installed; returns the
    venv's python executable path. ``--system-site-packages`` keeps the
    framework's own deps (cloudpickle, numpy, ...) importable inside."""
    import fcntl

    key = env_key(packages, use_uv)
    root = os.path.join(_env_root(), "venvs")
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, key)
    python = os.path.join(path, "bin", "python")
    marker = os.path.join(path, ".rt_ready")
    if os.path.exists(marker):
        return python
    with open(os.path.join(root, f".{key}.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return python  # another worker built it while we waited
        if os.path.exists(path):
            shutil.rmtree(path, ignore_errors=True)  # half-built leftover
        uv = shutil.which("uv") if use_uv else None
        if use_uv and not uv:
            # Fail loudly: pip's resolver can produce different installs
            # than the uv env the user tested with.
            raise RuntimeError(
                "runtime_env {'uv': ...} requested but the uv binary is "
                "not installed on this node (use {'pip': ...} instead)"
            )
        try:
            if uv:
                subprocess.run(
                    [uv, "venv", "--system-site-packages", path],
                    check=True, capture_output=True, timeout=timeout,
                )
                install = [uv, "pip", "install", "--python", python]
            else:
                subprocess.run(
                    [sys.executable, "-m", "venv",
                     "--system-site-packages", path],
                    check=True, capture_output=True, timeout=timeout,
                )
                install = [python, "-m", "pip", "install",
                           "--disable-pip-version-check"]
            if packages:
                res = subprocess.run(
                    install + list(packages),
                    capture_output=True, text=True, timeout=timeout,
                )
                if res.returncode != 0:
                    raise RuntimeError(
                        f"package install failed:\n{res.stderr[-2000:]}"
                    )
            with open(marker, "w") as f:
                f.write("ok")
        except Exception:
            shutil.rmtree(path, ignore_errors=True)
            raise
    return python
