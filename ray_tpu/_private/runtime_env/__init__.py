"""Per-task/actor runtime environments.

Reference behavior being reproduced (not copied):
``python/ray/_private/runtime_env/`` — pip/uv create cached virtualenvs
(``pip.py``, ``uv.py``), ``py_modules``/``working_dir`` are packaged,
content-addressed, uploaded, and downloaded to per-node caches
(``packaging.py``), and workers start inside the prepared env (the per-node
runtime-env agent, ``agent/runtime_env_agent.py``).

TPU-era design differences: there is no separate env agent process — the
node's worker prepares environments lazily on first use (creation happens on
the task executor thread, which already represents the task's slot), venvs
are content-hashed and shared machine-wide, and pip/uv tasks execute in a
dedicated per-env subprocess (``executor.py``) instead of re-launching the
whole worker: the process-per-host worker owns the TPU and must not be
recycled per env.

Supported plugins: env_vars, working_dir, py_modules, pip, uv, conda
(cached conda envs — ``conda.py``), image_uri (container executors).
Anything else fails loudly at execution time — silent degradation hid real
capability gaps (round-1 review finding).
"""
from __future__ import annotations

KNOWN_PLUGINS = ("env_vars", "working_dir", "py_modules", "pip", "uv",
                 "conda", "image_uri")


def validate(renv: dict):
    """Raise on unknown plugins — a task must not silently run without the
    environment it asked for."""
    from ray_tpu import exceptions as exc

    unknown = [k for k in (renv or {}) if k not in KNOWN_PLUGINS]
    if unknown:
        raise exc.RayTpuError(
            f"runtime_env plugins {unknown!r} are not supported "
            f"(supported: {list(KNOWN_PLUGINS)})"
        )
