"""Per-task/actor runtime environments.

Reference behavior being reproduced (not copied):
``python/ray/_private/runtime_env/`` — pip/uv create cached virtualenvs
(``pip.py``, ``uv.py``), ``py_modules``/``working_dir`` are packaged,
content-addressed, uploaded, and downloaded to per-node caches
(``packaging.py``), and workers start inside the prepared env (the per-node
runtime-env agent, ``agent/runtime_env_agent.py``).

TPU-era design differences: there is no separate env agent process — the
node's worker prepares environments lazily on first use (creation happens on
the task executor thread, which already represents the task's slot), venvs
are content-hashed and shared machine-wide, and pip/uv tasks execute in a
dedicated per-env subprocess (``executor.py``) instead of re-launching the
whole worker: the process-per-host worker owns the TPU and must not be
recycled per env.

Supported plugins: env_vars, working_dir, py_modules, pip, uv, conda
(cached conda envs — ``conda.py``), image_uri (container executors),
worker_process_setup_hook (once-per-process init callable — reference
``setup_hook.py``).
Anything else fails loudly at execution time — silent degradation hid real
capability gaps (round-1 review finding).
"""
from __future__ import annotations

KNOWN_PLUGINS = ("env_vars", "working_dir", "py_modules", "pip", "uv",
                 "conda", "image_uri", "worker_process_setup_hook")


def validate(renv: dict):
    """Raise on unknown plugins — a task must not silently run without the
    environment it asked for."""
    from ray_tpu import exceptions as exc

    unknown = [k for k in (renv or {}) if k not in KNOWN_PLUGINS]
    if unknown:
        raise exc.RayTpuError(
            f"runtime_env plugins {unknown!r} are not supported "
            f"(supported: {list(KNOWN_PLUGINS)})"
        )


# ---------------------------------------------------------------- setup hook

_SETUP_HOOKS_RAN = set()


def resolve_setup_hook(hook):
    """Hook spec -> callable: a submit-side pickled callable
    ({"__pickled_hook__": hex}) or a "module.attr" path."""
    if isinstance(hook, dict) and "__pickled_hook__" in hook:
        import cloudpickle

        return cloudpickle.loads(bytes.fromhex(hook["__pickled_hook__"]))
    import importlib

    mod, _, attr = str(hook).rpartition(".")
    if not mod:
        raise ValueError(
            f"worker_process_setup_hook {hook!r}: expected a callable or a "
            f"'module.attr' path"
        )
    return getattr(importlib.import_module(mod), attr)


def hook_key(hook) -> str:
    if isinstance(hook, dict) and "__pickled_hook__" in hook:
        return hook["__pickled_hook__"]
    return str(hook)


def run_setup_hook_once(hook) -> None:
    """Run the hook once per PROCESS (worker or env-executor child).
    Failures propagate — a task must not run half-initialized."""
    key = hook_key(hook)
    if key in _SETUP_HOOKS_RAN:
        return
    resolve_setup_hook(hook)()
    _SETUP_HOOKS_RAN.add(key)


class SetupHookTask:
    """Wraps a venv/conda/container-routed task so the env's setup hook
    runs inside the CHILD process (the process that executes the task)
    before the user function — the parent's once-per-process bookkeeping
    cannot cover a different process."""

    def __init__(self, hook, fn):
        self.hook = hook
        self.fn = fn

    def __call__(self, *args, **kwargs):
        run_setup_hook_once(self.hook)
        return self.fn(*args, **kwargs)
