"""Subprocess executor for tasks that run inside a runtime-env venv.

Reference analog: the worker-pool-per-runtime-env model (raylet worker pool
keyed by env hash; ``agent/runtime_env_agent.py`` prepares, workers launch
inside). Our worker is process-per-host and owns the TPU, so instead of
recycling whole workers per env, each distinct venv gets a lightweight
executor subprocess: the parent ships cloudpickled (fn, args, kwargs) over a
pipe, the child (running the venv's python) executes and ships back the
cloudpickled result. The child sees the venv's packages; numpy-style args
flow both ways because the venv uses --system-site-packages.
"""
from __future__ import annotations

import logging
import os
import struct
import subprocess
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")

# The child loop. Kept dependency-minimal: cloudpickle comes from the
# parent's site-packages (venvs are created with --system-site-packages).
_CHILD_SRC = r"""
import os, struct, sys, traceback
# The protocol channel is the ORIGINAL stdout fd, dup'd away before any
# user code runs; fd 1 is then pointed at stderr so task print() output
# cannot corrupt the length-prefixed wire framing.
_proto_fd = os.dup(1)
os.dup2(2, 1)
out = os.fdopen(_proto_fd, "wb")
# The parent's site-packages ride along as a FALLBACK (appended, so venv
# installs take precedence — except cloudpickle, pinned to the parent's
# copy below): `python -m venv` from a venv interpreter points system-site
# at the BASE prefix, losing the parent venv's packages (cloudpickle,
# numpy) that result shipping depends on.
_psite = [p for p in os.environ.get("RT_PARENT_SITE", "").split(os.pathsep) if p]
for _p in _psite:
    if _p not in sys.path:
        sys.path.append(_p)
# Protocol pin: the framed wire format is cloudpickle, and dumps/loads must
# run the SAME version on both ends (its reconstruction helpers are
# referenced by name; major-version gaps break loads). When the parent's
# site rides along, import ITS copy under the real module name — by-name
# references inside the stream then resolve to it too — instead of letting
# an image/venv-bundled older cloudpickle take over the protocol. This is
# the ONE package for which the env's own install does NOT win. Best-effort:
# if the parent's copy won't execute here (interpreter too old, mount
# unreadable), fall back to the env's own cloudpickle below.
import importlib.util as _ilu
for _p in _psite:
    _init = os.path.join(_p, "cloudpickle", "__init__.py")
    if os.path.exists(_init):
        try:
            _spec = _ilu.spec_from_file_location(
                "cloudpickle", _init,
                submodule_search_locations=[os.path.join(_p, "cloudpickle")])
            _mod = _ilu.module_from_spec(_spec)
            sys.modules["cloudpickle"] = _mod
            _spec.loader.exec_module(_mod)
        except BaseException:
            sys.modules.pop("cloudpickle", None)
            continue
        break
import cloudpickle

_U32 = struct.Struct("<I")
inp = sys.stdin.buffer

def read_exact(n):
    data = b""
    while len(data) < n:
        chunk = inp.read(n - len(data))
        if not chunk:
            raise SystemExit(0)
        data += chunk
    return data

while True:
    (n,) = _U32.unpack(read_exact(4))
    blob = read_exact(n)
    old_env, old_cwd = {}, None
    try:
        fn, args, kwargs, env_vars, cwd = cloudpickle.loads(blob)
        for k, v in (env_vars or {}).items():
            old_env[k] = os.environ.get(k)
            os.environ[k] = str(v)
        if cwd:
            old_cwd = os.getcwd()
            os.chdir(cwd)
        result = (True, fn(*args, **kwargs))
    except BaseException as e:
        result = (False, (repr(e), traceback.format_exc()))
    finally:
        if old_cwd is not None:
            try:
                os.chdir(old_cwd)
            except OSError:
                pass
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        reply = cloudpickle.dumps(result)
    except BaseException as e:
        # unpicklable return value: a task failure, not an executor crash
        reply = cloudpickle.dumps(
            (False, (f"task result not serializable: {e!r}",
                     traceback.format_exc()))
        )
    out.write(_U32.pack(len(reply)))
    out.write(reply)
    out.flush()
"""


class EnvExecutor:
    """One venv subprocess; tasks run serially per executor (the parent's
    task-slot accounting still bounds concurrency — one slot drives one
    executor call at a time)."""

    def __init__(self, python: str, path_entries: Optional[List[str]] = None,
                 argv: Optional[List[str]] = None,
                 inherit_parent_site: bool = True):
        """``argv`` overrides the child command entirely (the container
        plugin launches the SAME child loop via ``docker run -i ... python
        -c``; the framed stdin/stdout protocol is transport-agnostic).
        ``inherit_parent_site=False`` for conda envs, which stay fully
        isolated (cloudpickle is seeded into them at creation —
        ``conda._seed_cloudpickle``). Containers instead receive a
        RT_PARENT_SITE tail-fallback set by ``conda.container_argv`` so
        minimal images can still import cloudpickle; the child appends it
        AFTER the image's own sys.path, so image packages win — with the
        single exception of cloudpickle itself, which the child pins to
        the parent's copy because it IS the wire protocol (see
        ``_CHILD_SRC``)."""
        self.python = python
        env = dict(os.environ)
        # The child must import ray_tpu's deps (cloudpickle) and any staged
        # py_modules; prepend rather than replace.
        extra = list(path_entries or [])
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )))
        )
        extra.append(repo_root)
        prev = env.get("PYTHONPATH")
        env["PYTHONPATH"] = os.pathsep.join(
            extra + ([prev] if prev else [])
        )
        # Parent site-packages (appended by the child AFTER its own): see
        # _CHILD_SRC. sys.path is the honest source — site.getsitepackages
        # misses venv layouts.
        if inherit_parent_site:
            env["RT_PARENT_SITE"] = os.pathsep.join(
                p for p in sys.path if "site-packages" in p
            )
        self._lock = threading.Lock()
        # The task currently executing IN the child (set under _lock by
        # run()); the pressure killer's victim population.
        self.current_task: Optional[dict] = None
        self.proc = subprocess.Popen(
            argv or [python, "-u", "-c", _CHILD_SRC],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            env=env,
        )

    def alive(self) -> bool:
        return self.proc.poll() is None

    def run(self, fn, args, kwargs, env_vars: Optional[dict] = None,
            cwd: Optional[str] = None,
            task_info: Optional[dict] = None) -> Tuple[bool, Any]:
        """Returns (ok, result-or-(err_repr, traceback)). env_vars/cwd are
        applied PER CALL inside the child (executors are cached per venv, so
        per-task env differences must not bake into the process). Raises
        RuntimeError if the child died (caller treats as task failure and
        discards the executor). ``task_info`` is published as
        ``self.current_task`` ONLY while this call holds the child (inside
        the lock): the pressure killer must see the task actually running
        in the subprocess, not one queued behind it."""
        import cloudpickle

        blob = cloudpickle.dumps((fn, args, kwargs, env_vars, cwd))
        with self._lock:
            self.current_task = task_info
            if not self.alive():
                self.current_task = None
                raise RuntimeError("runtime-env executor process died")
            try:
                self.proc.stdin.write(_U32.pack(len(blob)))
                self.proc.stdin.write(blob)
                self.proc.stdin.flush()
                hdr = self.proc.stdout.read(4)
                if len(hdr) < 4:
                    raise RuntimeError(
                        "runtime-env executor exited mid-task"
                    )
                (n,) = _U32.unpack(hdr)
                data = b""
                while len(data) < n:
                    chunk = self.proc.stdout.read(n - len(data))
                    if not chunk:
                        raise RuntimeError(
                            "runtime-env executor exited mid-reply"
                        )
                    data += chunk
            except (BrokenPipeError, OSError) as e:
                raise RuntimeError(f"runtime-env executor pipe: {e}")
            finally:
                self.current_task = None
        return cloudpickle.loads(data)

    def close(self):
        try:
            self.proc.stdin.close()
        except Exception:
            pass
        try:
            self.proc.terminate()
            self.proc.wait(timeout=3)
        except Exception:
            try:
                self.proc.kill()
            except Exception:
                pass
