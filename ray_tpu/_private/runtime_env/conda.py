"""Conda + container (image_uri) runtime-env plugins.

Reference analogs: ``python/ray/_private/runtime_env/conda.py`` (cached
conda env creation keyed by the spec hash) and ``image_uri.py`` (worker
runs inside a container). Both reuse the venv plugins' executor-subprocess
model: the prepared interpreter runs the framed child loop from
``executor.py``; for containers the loop simply launches through
``docker run -i`` (or podman) — the stdin/stdout protocol is
transport-agnostic.

Both plugins fail LOUDLY when their binary (conda / docker / podman) is
absent: a task must not silently run outside the environment it asked for.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Union

from ray_tpu._private.runtime_env.venv import _env_root as _cache_root


def conda_env_key(spec: Union[List[str], Dict[str, Any]]) -> str:
    blob = json.dumps(spec, sort_keys=True).encode()
    return "conda-" + hashlib.sha256(blob).hexdigest()[:16]


def ensure_conda_env(spec: Union[List[str], Dict[str, Any]]) -> str:
    """Create (or reuse) a cached conda env; returns its python path.

    ``spec``: a package list (``{"conda": ["scipy=1.11"]}``) or a full
    environment dict (``{"dependencies": [...], "channels": [...]}``) —
    the same two shapes the reference accepts.
    """
    conda = shutil.which("conda") or shutil.which("mamba") \
        or shutil.which("micromamba")
    if conda is None:
        raise RuntimeError(
            "runtime_env 'conda' requires a conda/mamba binary on PATH; "
            "none found (use the 'pip' plugin for venv-based envs)"
        )
    root = _cache_root()
    prefix = os.path.join(root, conda_env_key(spec))
    python = os.path.join(prefix, "bin", "python")
    if os.path.exists(python):
        # Idempotent: envs cached before cloudpickle seeding existed (or
        # whose seed was wiped) must still heal on reuse — the executor
        # child cannot start without it.
        _seed_cloudpickle(prefix)
        return python
    tmp_prefix = prefix + ".tmp"
    shutil.rmtree(tmp_prefix, ignore_errors=True)
    if isinstance(spec, dict):
        import tempfile

        with tempfile.NamedTemporaryFile(
            "w", suffix=".yml", delete=False
        ) as f:
            try:
                import yaml

                yaml.safe_dump(spec, f)
            except ImportError:
                json.dump(spec, f)  # conda accepts JSON env files
            env_file = f.name
        cmd = [conda, "env", "create", "-p", tmp_prefix, "-f", env_file,
               "--yes"]
    else:
        cmd = [conda, "create", "-p", tmp_prefix, "--yes", "python",
               *list(spec)]
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    if res.returncode != 0:
        shutil.rmtree(tmp_prefix, ignore_errors=True)
        raise RuntimeError(
            f"conda env creation failed:\n{res.stderr[-2000:]}"
        )
    _seed_cloudpickle(tmp_prefix)
    os.replace(tmp_prefix, prefix)
    return python


def _seed_cloudpickle(prefix: str) -> None:
    """Copy the host's cloudpickle (pure python) into the env's
    site-packages: the executor child loop imports it before any task runs,
    and a newly created conda env does not ship it. Copying just this one
    package keeps the env isolated — no host site-packages fallback that
    would silently satisfy imports the declared env is missing.

    Also runs as a heal on cache hits, so it must be atomic and
    race-tolerant: copy to a temp name, rename into place (losers of a
    concurrent race just discard their temp), and treat a dir missing
    ``__init__.py`` — an interrupted earlier copy — as absent."""
    import glob

    import cloudpickle

    src = os.path.dirname(cloudpickle.__file__)
    for site in glob.glob(
        os.path.join(prefix, "lib", "python*", "site-packages")
    ):
        dst = os.path.join(site, "cloudpickle")
        if os.path.exists(os.path.join(dst, "__init__.py")):
            continue
        tmp = f"{dst}.tmp{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.copytree(src, tmp)
        shutil.rmtree(dst, ignore_errors=True)  # partial leftover, if any
        try:
            os.replace(tmp, dst)
        except OSError:  # a concurrent seeder won the rename
            shutil.rmtree(tmp, ignore_errors=True)


def container_argv(image_uri: str, child_src: str,
                   path_entries: Optional[List[str]] = None,
                   working_dir: Optional[str] = None) -> List[str]:
    """argv that runs the executor child loop inside a container
    (reference: ``image_uri.py`` — podman-launched workers). The repo,
    staged py_modules, and the task's working_dir are bind-mounted at
    their HOST paths so cloudpickled functions, sys.path entries, and
    os.chdir targets resolve inside the container; PYTHONPATH is set
    in-container (the docker client's env never crosses the boundary)."""
    runtime = shutil.which("podman") or shutil.which("docker")
    if runtime is None:
        raise RuntimeError(
            "runtime_env 'image_uri' requires podman or docker on PATH; "
            "neither found"
        )
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )))
    entries = [os.path.abspath(e) for e in (path_entries or ())]
    # Host site-packages ride along read-only as a TAIL fallback so the
    # child loop can import cloudpickle (pure-python) even in minimal
    # images. They go through RT_PARENT_SITE — which the child loop appends
    # AFTER the image interpreter's own sys.path — never PYTHONPATH, whose
    # entries would precede the image's site-packages and silently shadow
    # the very packages image_uri was asked to provide.
    host_site = [p for p in sys.path if "site-packages" in p]
    pythonpath = os.pathsep.join([*entries, repo_root])
    argv = [runtime, "run", "--rm", "-i",
            "-v", f"{repo_root}:{repo_root}:ro",
            "-e", f"PYTHONPATH={pythonpath}",
            "-e", f"RT_PARENT_SITE={os.pathsep.join(host_site)}"]
    for e in entries:
        argv += ["-v", f"{e}:{e}:ro"]
    for sp in host_site:
        argv += ["-v", f"{sp}:{sp}:ro"]
    if working_dir:
        wd = os.path.abspath(working_dir)
        argv += ["-v", f"{wd}:{wd}"]
    argv += [image_uri, "python", "-u", "-c", child_src]
    return argv
