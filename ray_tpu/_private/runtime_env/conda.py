"""Conda + container (image_uri) runtime-env plugins.

Reference analogs: ``python/ray/_private/runtime_env/conda.py`` (cached
conda env creation keyed by the spec hash) and ``image_uri.py`` (worker
runs inside a container). Both reuse the venv plugins' executor-subprocess
model: the prepared interpreter runs the framed child loop from
``executor.py``; for containers the loop simply launches through
``docker run -i`` (or podman) — the stdin/stdout protocol is
transport-agnostic.

Both plugins fail LOUDLY when their binary (conda / docker / podman) is
absent: a task must not silently run outside the environment it asked for.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Union

from ray_tpu._private.runtime_env.venv import _env_root as _cache_root


def conda_env_key(spec: Union[List[str], Dict[str, Any]]) -> str:
    blob = json.dumps(spec, sort_keys=True).encode()
    return "conda-" + hashlib.sha256(blob).hexdigest()[:16]


def ensure_conda_env(spec: Union[List[str], Dict[str, Any]]) -> str:
    """Create (or reuse) a cached conda env; returns its python path.

    ``spec``: a package list (``{"conda": ["scipy=1.11"]}``) or a full
    environment dict (``{"dependencies": [...], "channels": [...]}``) —
    the same two shapes the reference accepts.
    """
    conda = shutil.which("conda") or shutil.which("mamba") \
        or shutil.which("micromamba")
    if conda is None:
        raise RuntimeError(
            "runtime_env 'conda' requires a conda/mamba binary on PATH; "
            "none found (use the 'pip' plugin for venv-based envs)"
        )
    root = _cache_root()
    prefix = os.path.join(root, conda_env_key(spec))
    python = os.path.join(prefix, "bin", "python")
    if os.path.exists(python):
        return python
    tmp_prefix = prefix + ".tmp"
    shutil.rmtree(tmp_prefix, ignore_errors=True)
    if isinstance(spec, dict):
        import tempfile

        with tempfile.NamedTemporaryFile(
            "w", suffix=".yml", delete=False
        ) as f:
            try:
                import yaml

                yaml.safe_dump(spec, f)
            except ImportError:
                json.dump(spec, f)  # conda accepts JSON env files
            env_file = f.name
        cmd = [conda, "env", "create", "-p", tmp_prefix, "-f", env_file,
               "--yes"]
    else:
        cmd = [conda, "create", "-p", tmp_prefix, "--yes", "python",
               *list(spec)]
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    if res.returncode != 0:
        shutil.rmtree(tmp_prefix, ignore_errors=True)
        raise RuntimeError(
            f"conda env creation failed:\n{res.stderr[-2000:]}"
        )
    os.replace(tmp_prefix, prefix)
    return python


def container_argv(image_uri: str, child_src: str,
                   path_entries: Optional[List[str]] = None,
                   working_dir: Optional[str] = None) -> List[str]:
    """argv that runs the executor child loop inside a container
    (reference: ``image_uri.py`` — podman-launched workers). The repo,
    staged py_modules, and the task's working_dir are bind-mounted at
    their HOST paths so cloudpickled functions, sys.path entries, and
    os.chdir targets resolve inside the container; PYTHONPATH is set
    in-container (the docker client's env never crosses the boundary)."""
    runtime = shutil.which("podman") or shutil.which("docker")
    if runtime is None:
        raise RuntimeError(
            "runtime_env 'image_uri' requires podman or docker on PATH; "
            "neither found"
        )
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )))
    entries = [os.path.abspath(e) for e in (path_entries or ())]
    # Host site-packages ride along read-only as a TAIL fallback so the
    # child loop can import cloudpickle (pure-python) even in minimal
    # images; the image's own packages win (PYTHONPATH order).
    host_site = [p for p in sys.path if "site-packages" in p]
    pythonpath = os.pathsep.join([*entries, repo_root, *host_site])
    argv = [runtime, "run", "--rm", "-i",
            "-v", f"{repo_root}:{repo_root}:ro",
            "-e", f"PYTHONPATH={pythonpath}"]
    for e in entries:
        argv += ["-v", f"{e}:{e}:ro"]
    for sp in host_site:
        argv += ["-v", f"{sp}:{sp}:ro"]
    if working_dir:
        wd = os.path.abspath(working_dir)
        argv += ["-v", f"{wd}:{wd}"]
    argv += [image_uri, "python", "-u", "-c", child_src]
    return argv
