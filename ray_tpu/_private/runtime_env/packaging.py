"""py_modules / working_dir packaging: zip, content-address, stage in the
head's KV, download + unpack into a per-machine cache.

Reference analog: ``python/ray/_private/runtime_env/packaging.py`` —
``gcs://_ray_pkg_<hash>.zip`` URIs with local caches. Here the head KV is
the package store (runtime-env packages are code, i.e. small; a size cap
keeps datasets out of the control plane).
"""
from __future__ import annotations

import hashlib
import io
import logging
import os
import tempfile
import zipfile
from typing import List

logger = logging.getLogger(__name__)

PKG_NS = "_renv_pkgs"
MAX_PKG_BYTES = 64 * 1024 * 1024


def _zip_path(path: str) -> bytes:
    """Deterministic zip of a file or directory (stable order, no mtimes —
    the hash must be content-only)."""
    buf = io.BytesIO()
    base = os.path.basename(os.path.normpath(path))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        if os.path.isfile(path):
            z.writestr(base, open(path, "rb").read())
        else:
            for root, dirs, files in os.walk(path):
                dirs.sort()
                for f in sorted(files):
                    if f.endswith(".pyc") or "__pycache__" in root:
                        continue
                    full = os.path.join(root, f)
                    rel = os.path.join(
                        base, os.path.relpath(full, path)
                    )
                    zi = zipfile.ZipInfo(rel)  # zeroed date_time
                    z.writestr(zi, open(full, "rb").read())
    data = buf.getvalue()
    if len(data) > MAX_PKG_BYTES:
        raise ValueError(
            f"py_modules package {path!r} is {len(data)/1e6:.0f}MB; "
            f"cap is {MAX_PKG_BYTES/1e6:.0f}MB (ship data via the object "
            f"store, not the code path)"
        )
    return data


def stage_modules(worker, paths: List[str]) -> List[dict]:
    """Driver side: upload each local module path once; returns wire
    descriptors [{"hash", "name"}]. Already-staged hashes are skipped (the
    head KV is the cache)."""
    out = []
    for path in paths:
        if isinstance(path, dict):  # already staged (actor restart replay)
            out.append(path)
            continue
        data = _zip_path(path)
        h = hashlib.sha256(data).hexdigest()[:24]
        key = f"pkg_{h}"
        cached = getattr(worker, "_staged_renv_pkgs", None)
        if cached is None:
            cached = worker._staged_renv_pkgs = set()
        if h not in cached:
            hdr, _ = worker.run_sync(
                worker.gcs.call(
                    "kv_exists", {"ns": PKG_NS, "key": key}
                )
            )
            if not hdr.get("exists"):
                worker.run_sync(
                    worker.gcs.call(
                        "kv_put",
                        {"ns": PKG_NS, "key": key},
                        [data],
                    )
                )
            cached.add(h)
        out.append({
            "hash": h, "name": os.path.basename(os.path.normpath(path)),
        })
    return out


def fetch_modules(worker, descriptors: List[dict]) -> List[str]:
    """Executor side: ensure each package is unpacked locally; returns
    sys.path entries. Cache dir is content-addressed so concurrent fetches
    of the same package are idempotent (tempdir + atomic rename)."""
    root = os.environ.get("RT_RUNTIME_ENV_DIR") or os.path.join(
        tempfile.gettempdir(), f"rt_runtime_env_{os.getuid()}"
    )
    pkg_root = os.path.join(root, "pkgs")
    os.makedirs(pkg_root, exist_ok=True)
    entries = []
    for d in descriptors:
        dest = os.path.join(pkg_root, d["hash"])
        if not os.path.isdir(dest):
            hdr, frames = worker.run_sync(
                worker.gcs.call(
                    "kv_get", {"ns": PKG_NS, "key": f"pkg_{d['hash']}"}
                )
            )
            if not hdr.get("found"):
                raise RuntimeError(
                    f"py_modules package {d['hash']} missing from the head"
                )
            tmp = tempfile.mkdtemp(dir=pkg_root)
            with zipfile.ZipFile(io.BytesIO(bytes(frames[0]))) as z:
                z.extractall(tmp)
            try:
                os.rename(tmp, dest)
            except OSError:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        entries.append(dest)
    return entries
