"""Task-centric critical-path analysis over the flight recorder.

The flight recorder (``_private/flight.py``) attributes time to RPC
*verbs*; the state API buffers task lifecycle *events*. This module joins
the two planes around one key — the task id — so a single task's life is
traceable submit→lease→push→arg-pull→exec→result→reply across processes:

- **Recording**: the worker stamps ``task.<stage>`` spans into the same
  per-process flight ring the RPC hooks use (kind ``"task"``, cid = task
  id), and observes each stage into the ``rt_task_phase_seconds{phase,fn}``
  histogram that rides the existing metrics_push → head ``/metrics``
  rollup. Everything is gated on ``flight.ENABLED`` — disabled, the hot
  paths pay the same one-boolean check as every other flight hook.
- **Analysis**: :func:`task_breakdown` splits one task's wall time into
  named phases with the residual reported explicitly (never silently
  absorbed); :func:`phase_table` aggregates per-function p50/p99 phase
  stats. Surfaces: ``rt timeline --task``, ``rt flight --task-attrib``,
  ``state.summarize_tasks(phases=True)``, ``bench.py --phases``.
- **Join**: :func:`task_events_to_merged` lifts the state API's task
  events into the merged-span dict shape, so ``flight.to_chrome_trace``
  draws task tracks WITH flow links into the RPC spans that share the id.

Phase model (wall time measured on the DRIVER's clock, so clock skew can
never corrupt the sum; executor-side contributions are pure durations):

    wall      = task.submit start → task.push end
    submit      serialize args / export fn / enqueue       (driver span)
    submit-queue | lease-wait | warm-pool-hit               (driver span;
                the queued span's outcome names which wait it was)
    fn-push | kv-get                                        (executor span;
                outcome says whether the fn blob rode push-through or a
                head kv_get round-trip)
    arg-pull    materialize argument refs                   (executor span)
    exec-queue  executor-side wait between arrival and the first
                instrumented serve work — ring chunks queueing in the
                executor pool behind earlier chunks, loop scheduling on
                the slow path (derived: serve − inner durations). Before
                Round-15 this hid inside reply-ack
    exec        user function runtime                       (executor span)
    result-push serialize + store + register results        (executor span)
    reply-window time a packaged result sat in the executor's coalescing
                reply window before its multi-result frame went out
                (executor span; zero when reply batching is off or the
                result opened an idle window)
    pump-queue  time a reply frame sat between arrival at the DRIVER's
                transport (ring pump pop / TCP recv) and its future
                settling — loop handoff + settle queueing on a
                saturated driver, measured entirely on the driver's
                clock (Round 16 carved this out of reply-ack; the
                multi-frame settle drain is what shrinks it). With the
                Round-20 settle plane the span ends at the plane
                HANDOFF, not the settle
    settle-dwell time a handed-off reply frame spent on the driver's
                settle plane — worker-queue depth plus the cross-loop
                hop back to the owning futures (driver clock; zero when
                driver_settle_thread is off, the dwell then stays in
                pump-queue)
    reply-ack   push RTT not covered by the executor's serve envelope,
                the reply window, or the driver's pump-queue dwell:
                wire both ways + connection queuing (derived). For
                chunked pushes this includes waiting behind chunk-mates
                on the executor — the driver's per-task push span
                starts at chunk send
    residual    wall − sum(above) — dispatch gaps, server queueing not
                inside any named phase. Always shown.

Cold worker-spawn time surfaces under ``lease-wait`` (the head blocks the
grant until capacity exists); a warm-pool activation is named explicitly
because the head tags the grant that flipped a standby node.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from ray_tpu._private import flight

logger = logging.getLogger(__name__)

# Canonical phase order for tables and rollups (residual always last).
PHASES = (
    "submit", "submit-queue", "lease-wait", "warm-pool-hit",
    "fn-push", "kv-get", "arg-pull", "exec-queue", "exec", "result-push",
    "reply-window", "pump-queue", "settle-dwell", "reply-ack", "residual",
)

# task.queued outcome -> phase name (see worker._pop_pending).
_QUEUE_PHASES = {
    "submit-queue": "submit-queue",
    "lease-wait": "lease-wait",
    "warm-pool-hit": "warm-pool-hit",
    # actor calls: queue time is channel/creation wait, closest to lease
    "actor-pending": "lease-wait",
}

_hist = None


def observe_phase(phase: str, fn: str, seconds: float):
    """One observation into ``rt_task_phase_seconds{phase,fn}``. The
    histogram rides the per-process metrics registry, reaching the head's
    aggregated ``/metrics`` through the same metrics_push pipeline as
    every other series. Call sites gate on ``flight.ENABLED``."""
    global _hist
    h = _hist
    if h is None:
        try:
            from ray_tpu.util.metrics import Histogram

            h = _hist = Histogram(
                "rt_task_phase_seconds",
                description="Per-task phase durations (taskpath plane)",
                boundaries=(
                    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                    0.5, 1.0, 5.0, 30.0,
                ),
                tag_keys=("phase", "fn"),
            )
        except Exception as e:
            logger.debug("rt_task_phase_seconds unavailable: %s", e)
            return
    # Bounded tag cardinality: fn is a function/method name, not user data.
    h.observe(seconds, tags={"phase": phase, "fn": (fn or "task")[:64]})


def record_phase(stage: str, tid, t0: float, t1: float, *, fn: str = "",
                 nbytes: int = 0, outcome: str = "ok",
                 phase: Optional[str] = None):
    """Record one ``task.<stage>`` span (cid = task id, kind ``task``)
    and, when ``phase`` is given, observe it into the rollup histogram."""
    flight.record(f"task.{stage}", tid, "task", t0, t1, nbytes, outcome)
    if phase is not None:
        observe_phase(phase, fn, t1 - t0)


# ------------------------------------------------------------------ analysis

def _by_task(merged: List[Dict[str, Any]]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for e in merged:
        if e.get("kind") == "task" and e.get("cid"):
            out.setdefault(str(e["cid"]), []).append(e)
    return out


def _names_by_tid(events) -> Dict[str, str]:
    return {
        str(ev.get("task_id")): str(ev.get("name") or "task")
        for ev in events or ()
        if ev.get("task_id")
    }


def task_breakdown(merged: List[Dict[str, Any]], task_id: str,
                   events=None) -> Optional[Dict[str, Any]]:
    """Split one task's wall time into named phases. Returns None when no
    ``task.*`` span carries the id. Retried stages sum their attempts.

    ``sum(phases.values()) == wall`` holds by construction: the residual
    is an explicit phase, never silently absorbed."""
    spans = _by_task(merged).get(str(task_id))
    if not spans:
        return None
    dur: Dict[str, float] = {}
    outcomes: Dict[str, str] = {}
    for e in spans:
        stage = e["verb"]
        dur[stage] = dur.get(stage, 0.0) + float(e["dur"])
        if e.get("outcome") and e["outcome"] != "ok":
            outcomes[stage] = str(e["outcome"])
    phases = {p: 0.0 for p in PHASES}
    phases["submit"] = dur.get("task.submit", 0.0)
    qphase = _QUEUE_PHASES.get(
        outcomes.get("task.queued", "submit-queue"), "submit-queue"
    )
    phases[qphase] += dur.get("task.queued", 0.0)
    fn_phase = (
        "kv-get" if outcomes.get("task.fn_load", "").startswith("kv_get")
        else "fn-push"
    )
    phases[fn_phase] += dur.get("task.fn_load", 0.0)
    phases["arg-pull"] = dur.get("task.arg_pull", 0.0)
    phases["exec"] = dur.get("task.exec", 0.0)
    phases["result-push"] = dur.get("task.result", 0.0)
    # Window dwell is measured executor-side (a duration, skew-free) so
    # reply-ack stays what its name says — wire both ways + connection
    # queuing — even when the result rode a coalesced frame.
    phases["reply-window"] = dur.get("task.reply_window", 0.0)
    # Round 16: reply dwell between the driver's transport arrival and
    # the future settle (driver clock both ends) — carved out of the
    # derived reply-ack the same way reply-window was. Round 20 splits
    # it at the settle-plane handoff stamp: arrival->handoff stays
    # pump-queue (transport-side), handoff->settle is the plane's own
    # dwell (queue depth + the cross-loop hop).
    phases["pump-queue"] = dur.get("task.pump_queue", 0.0)
    phases["settle-dwell"] = dur.get("task.settle_dwell", 0.0)
    push = dur.get("task.push", 0.0)
    inner = (
        phases[fn_phase] + phases["arg-pull"] + phases["exec"]
        + phases["result-push"]
    )
    serve = max(dur.get("task.serve", 0.0), inner)
    # The serve envelope starts at ARRIVAL on every path (Round-15 moved
    # the ring spans from exec-start to the pump's chunk stamp), so the
    # executor-side wait before instrumented work — chunks queueing in
    # the executor pool — is its own truthful phase instead of hiding in
    # the derived reply-ack. All durations, skew-free.
    phases["exec-queue"] = max(serve - inner, 0.0)
    phases["reply-ack"] = max(
        push - serve - phases["reply-window"] - phases["pump-queue"]
        - phases["settle-dwell"], 0.0
    )
    # Wall: driver-clock envelope. All driver spans live in one process,
    # so ts arithmetic is skew-free; fall back to the span extent when a
    # stage was sampled out or overwritten in the ring.
    starts = [e["ts"] for e in spans]
    ends = [e["ts"] + e["dur"] for e in spans]
    sub = [e for e in spans if e["verb"] == "task.submit"]
    psh = [e for e in spans if e["verb"] == "task.push"]
    t0 = min(e["ts"] for e in sub) if sub else min(starts)
    t1 = max(e["ts"] + e["dur"] for e in psh) if psh else max(ends)
    wall = max(t1 - t0, 0.0)
    named = sum(v for p, v in phases.items() if p != "residual")
    phases["residual"] = max(wall - named, 0.0)
    name = _names_by_tid(events).get(str(task_id), "")
    return {
        "task_id": str(task_id),
        "fn": name,
        "wall_s": wall,
        "phases": phases,
        "outcomes": outcomes,
        "spans": len(spans),
    }


def breakdown_all(merged: List[Dict[str, Any]],
                  events=None) -> List[Dict[str, Any]]:
    names = _names_by_tid(events)
    out = []
    for tid in _by_task(merged):
        b = task_breakdown(merged, tid, events=None)
        if b is None:
            continue
        b["fn"] = names.get(tid, b["fn"])
        out.append(b)
    return out


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]


def phase_table(merged: List[Dict[str, Any]],
                events=None) -> Dict[str, Dict[str, Dict[str, float]]]:
    """Per-function phase statistics: {fn: {phase: {count, total_s,
    p50_ms, p99_ms}}} over every task with spans in ``merged``. This is
    the ``rt flight --task-attrib`` / ``bench.py --phases`` table."""
    by_fn: Dict[str, Dict[str, List[float]]] = {}
    for b in breakdown_all(merged, events):
        fn = b["fn"] or "task"
        rec = by_fn.setdefault(fn, {p: [] for p in PHASES})
        for p, v in b["phases"].items():
            rec[p].append(v)
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for fn, rec in by_fn.items():
        out[fn] = {}
        for p, vals in rec.items():
            if not vals or not any(v > 0.0 for v in vals):
                continue
            vs = sorted(vals)
            out[fn][p] = {
                "count": len(vs),
                "total_s": sum(vs),
                "p50_ms": _pct(vs, 0.50) * 1e3,
                "p99_ms": _pct(vs, 0.99) * 1e3,
            }
    return out


# ---------------------------------------------------------------- rendering

def format_task_timeline(b: Dict[str, Any]) -> str:
    """Fixed-width phase breakdown for one task (``rt timeline --task``)."""
    wall = b["wall_s"]
    lines = [
        f"task {b['task_id']}"
        + (f"  fn={b['fn']}" if b["fn"] else "")
        + f"  wall={wall * 1e3:.3f}ms  ({b['spans']} spans)",
        f"{'phase':<16}{'ms':>12}{'% wall':>9}",
    ]
    for p in PHASES:
        v = b["phases"].get(p, 0.0)
        if v <= 0.0 and p != "residual":
            continue
        pct = (v / wall * 100.0) if wall > 0 else 0.0
        lines.append(f"{p:<16}{v * 1e3:>12.3f}{pct:>8.1f}%")
    named = sum(b["phases"].values())
    lines.append(f"{'sum':<16}{named * 1e3:>12.3f}"
                 f"{(named / wall * 100.0 if wall > 0 else 0.0):>8.1f}%")
    return "\n".join(lines)


def format_phase_table(table: Dict[str, Dict[str, Dict[str, float]]]) -> str:
    """Fixed-width per-function phase table, heaviest functions first."""
    lines = [
        f"{'fn':<20}{'phase':<16}{'count':>7}{'total_s':>9}"
        f"{'p50_ms':>9}{'p99_ms':>9}"
    ]
    rows = sorted(
        table.items(),
        key=lambda kv: -sum(s["total_s"] for s in kv[1].values()),
    )
    for fn, phases in rows:
        first = True
        for p in PHASES:
            s = phases.get(p)
            if s is None:
                continue
            lines.append(
                f"{(fn[:19] if first else ''):<20}{p:<16}"
                f"{s['count']:>7}{s['total_s']:>9.3f}"
                f"{s['p50_ms']:>9.3f}{s['p99_ms']:>9.3f}"
            )
            first = False
    return "\n".join(lines)


# ------------------------------------------------------------------- joins

def task_events_to_merged(events) -> List[Dict[str, Any]]:
    """Lift state-API task events into the merged-span dict shape, so
    ``flight.to_chrome_trace`` renders per-task tracks and stitches flow
    links into every RPC span sharing the task's join key (the events
    carry ``cid`` = task id, plus the RPC ``corr`` for actor pushes)."""
    out: List[Dict[str, Any]] = []
    for ev in events or ():
        try:
            t0 = float(ev["start_time"])
            t1 = float(ev.get("end_time", t0))
        except (KeyError, TypeError, ValueError):
            continue
        node = str(ev.get("node_id") or "node")[:8]
        out.append({
            "proc": f"task:{node}",
            "pid": node,
            "verb": f"{ev.get('name') or 'task'}"
                    f" [{ev.get('state', '?')}]",
            "cid": ev.get("cid") or ev.get("task_id"),
            "kind": "task",
            "ts": t0,
            "dur": max(t1 - t0, 0.0),
            "nbytes": 0,
            "outcome": str(ev.get("state", "?")),
            "qw": 0.0,
        })
        # Actor pushes also join on the RPC corr id: a second merged
        # entry would double-count attribution, so the corr join rides
        # a zero-duration instant at task start instead.
        corr = ev.get("corr")
        if corr and corr != ev.get("cid"):
            out.append({
                "proc": f"task:{node}", "pid": node,
                "verb": f"{ev.get('name') or 'task'} [corr]",
                "cid": corr, "kind": "task", "ts": t0, "dur": 0.0,
                "nbytes": 0, "outcome": "join", "qw": 0.0,
            })
    out.sort(key=lambda e: e["ts"])
    return out
