"""Chaos/test helpers.

Reference analog: ``python/ray/_private/test_utils.py`` —
``ResourceKillerActor`` (:1278), ``RayletKiller`` (:1407): background
killers that take out cluster components mid-workload so fault-tolerance
paths get exercised for real. Per-RPC fault injection (the finer-grained
chaos plane) lives in ``_private/faultpoints.py``.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import List, Optional, Tuple

from ray_tpu._private.backoff import Backoff

logger = logging.getLogger(__name__)


class NodeKiller:
    """Kills random worker nodes of a LocalCluster on an interval.

    Spares the last ``min_alive`` nodes so the workload can finish. Runs in
    a thread in the driver (our cluster handle lives there; the reference
    runs its killer as an actor for remote clusters). Kills that fail are
    logged and recorded in ``kill_errors`` — a chaos run whose killer
    silently stopped killing proves nothing.
    """

    def __init__(self, cluster, interval_s: float = 1.0, min_alive: int = 1,
                 max_kills: int = 1_000_000, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.min_alive = min_alive
        self.max_kills = max_kills
        self.killed: List[str] = []
        self.kill_errors: List[Tuple[str, str]] = []  # (node_id, error)
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="rt-node-killer"
        )
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set() and len(self.killed) < self.max_kills:
            if self._stop.wait(self.interval_s):
                return
            alive = [n for n in self.cluster.nodes if n.alive()]
            if len(alive) <= self.min_alive:
                continue
            victim = self._rng.choice(alive)
            try:
                self.cluster.kill_node(victim)
                self.killed.append(victim.node_id)
            except Exception as e:
                logger.debug("NodeKiller: kill of node %s failed: %s",
                             victim.node_id[:8], e)
                self.kill_errors.append((victim.node_id, repr(e)))

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


def wait_for_condition(fn, timeout: float = 30.0, interval: float = 0.1,
                       message: str = "condition not met"):
    """Poll ``fn`` until truthy. ``interval`` is the BASE delay of a
    jittered backoff (RT204: constant-period polls synchronize
    contenders), capped a few doublings above it so a slow condition
    doesn't turn into multi-second blind spots."""
    deadline = time.monotonic() + timeout
    poll = Backoff(base=interval, cap=max(interval * 8, interval))
    while time.monotonic() < deadline:
        if fn():
            return
        poll.sleep()
    raise TimeoutError(message)
