"""Shared asyncio helpers for the framework planes.

``spawn_logged`` is the sanctioned fire-and-forget: a bare
``loop.create_task(coro())`` whose handle is dropped swallows the
coroutine's exception until interpreter shutdown (asyncio only reports
it when the task object is garbage-collected — for a long-lived driver
that can be never). The lint rule RT303 flags exactly that shape;
every background spawn in ``_private/`` goes through here instead, so
a dying flusher/pusher/reaper leaves a log line pointing at itself.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional

logger = logging.getLogger(__name__)


def _report(task: "asyncio.Task", what: str) -> None:
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        logger.error("background task %s failed: %r", what, exc,
                     exc_info=exc)


def spawn_logged(loop: Optional[asyncio.AbstractEventLoop],
                 coro: Coroutine, what: str) -> "asyncio.Task":
    """``create_task`` + an exception-logging done callback.

    ``loop=None`` uses the running loop (call from coroutines only).
    ``what`` names the task in the failure log line (and the asyncio
    task name, for ``rt timeline`` / debugger legibility).
    """
    if loop is None:
        loop = asyncio.get_running_loop()
    task = loop.create_task(coro)
    try:
        task.set_name(f"rt:{what}")
    except AttributeError:  # pragma: no cover - very old loops
        pass
    task.add_done_callback(lambda t: _report(t, what))
    return task


def spawn_threadsafe(loop: asyncio.AbstractEventLoop,
                     coro: Coroutine, what: str):
    """``spawn_logged`` across threads (round 20): schedule ``coro`` on a
    loop owned by ANOTHER thread — the driver's main loop handing a
    pusher to a shard loop — with the same you-will-hear-about-failures
    contract. Returns the ``concurrent.futures.Future`` tracking the
    coroutine."""
    fut = asyncio.run_coroutine_threadsafe(coro, loop)

    def _report_cf(f):
        if f.cancelled():
            return
        exc = f.exception()
        if exc is not None:
            logger.error("background task %s failed: %r", what, exc,
                         exc_info=exc)

    fut.add_done_callback(_report_cf)
    return fut
