"""TPU accelerator manager: env/metadata detection + slice resources.

Reference analog: ``python/ray/_private/accelerators/tpu.py`` —
``TPUAcceleratorManager`` (:316): chip autodetect (:343), visibility env
``TPU_VISIBLE_CHIPS`` (:432), pod type/topology from GCE instance metadata
(:475-588), and the extra ``TPU-{pod}-head`` resource on worker 0 (:634)
that lets the scheduler reserve an ICI-connected slice atomically.

Detection here is env-first (TPU VM images export TPU_* vars), with the GCE
metadata server as fallback; both layers are injectable for tests (the
reference mocks the same seams in ``tests/accelerators/test_tpu.py``).
"""
from __future__ import annotations

import glob
import logging
import os
import re
from typing import Dict, List, Optional

from ray_tpu._private.accelerators.accelerator import (
    AcceleratorManager,
    register_accelerator_manager,
)

logger = logging.getLogger(__name__)

_GCE_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"
)

# chips per host by generation (v4/v5p: 4 chips, v5e/v6e: up to 8)
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 8,
                   "v5e": 8, "v6e": 8}


_metadata_cache: Dict[str, Optional[str]] = {}


def _fetch_metadata(key: str, timeout: float = 1.0) -> Optional[str]:
    """GCE metadata attribute (None off-GCE), cached per process — the
    detection paths re-query the same keys and off-GCE lookups can block on
    DNS. Patched in tests (patched versions bypass the cache)."""
    if key in _metadata_cache:
        return _metadata_cache[key]
    import urllib.request

    try:
        req = urllib.request.Request(
            _GCE_METADATA_URL + key, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            value = r.read().decode()
    except Exception:
        value = None
    _metadata_cache[key] = value
    return value


@register_accelerator_manager
class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    # ---------------------------------------------------------- detection

    @staticmethod
    def _accelerator_type() -> Optional[str]:
        """e.g. "v5e-16": env first, then GCE metadata."""
        for var in ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE"):
            v = os.environ.get(var)
            if v:
                return v
        return _fetch_metadata("accelerator-type")

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        # explicit override first (also the test seam)
        v = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
        if v:  # "2,2,1" style bounds
            try:
                dims = [int(x) for x in v.split(",")]
                n = 1
                for d in dims:
                    n *= d
                return n
            except ValueError:
                pass
        # device files exposed on TPU VMs (/dev/vfio/vfio is the container
        # control node, not a chip)
        n = len(glob.glob("/dev/accel*")) or len(
            [p for p in glob.glob("/dev/vfio/*") if not p.endswith("/vfio")]
        )
        if n:
            return n
        acc = TPUAcceleratorManager._accelerator_type()
        if acc:
            gen = acc.split("-")[0]
            per_host = _CHIPS_PER_HOST.get(gen, 4)
            total = TPUAcceleratorManager._num_chips_in_slice(acc) or per_host
            return min(per_host, total)
        return 0

    @staticmethod
    def _num_chips_in_slice(acc_type: str) -> int:
        m = re.match(r"v\w+-(\d+)$", acc_type or "")
        return int(m.group(1)) if m else 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        acc = TPUAcceleratorManager._accelerator_type()
        return f"TPU-{acc.split('-')[0].upper()}" if acc else None

    @staticmethod
    def _worker_id() -> int:
        v = os.environ.get("TPU_WORKER_ID")
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
        v = _fetch_metadata("agent-worker-number")
        return int(v) if v and v.isdigit() else 0

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Worker 0 of a slice advertises ``TPU-{type}-head: 1`` so a single
        bundle can reserve the whole ICI slice (reference: ``tpu.py:634``)."""
        acc = TPUAcceleratorManager._accelerator_type()
        if acc and TPUAcceleratorManager._worker_id() == 0:
            return {f"TPU-{acc}-head": 1.0}
        return {}

    @staticmethod
    def get_current_node_labels() -> Dict[str, str]:
        acc = TPUAcceleratorManager._accelerator_type()
        if not acc:
            return {}
        labels = {
            "ray_tpu.accelerator_type": acc,
            "ray_tpu.tpu_worker_id": str(TPUAcceleratorManager._worker_id()),
        }
        name = os.environ.get("TPU_NAME") or _fetch_metadata("instance-id")
        if name:
            labels["ray_tpu.slice_name"] = str(name)
        topo = os.environ.get("TPU_TOPOLOGY")
        if not topo:
            # tpu-env is a multi-line "KEY: 'value'" blob; extract TOPOLOGY
            blob = _fetch_metadata("tpu-env")
            if blob:
                m = re.search(r"TOPOLOGY:\s*'?([0-9x]+)'?", blob)
                topo = m.group(1) if m else None
        if topo:
            labels["ray_tpu.topology"] = topo.strip()
        return labels

    # ---------------------------------------------------------- visibility

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> Optional[str]:
        return "TPU_VISIBLE_CHIPS"

    @staticmethod
    def set_visible_accelerators(ids: List[str], env: Dict[str, str]):
        """Reference ``tpu.py:432``: scope a worker to a subset of local
        chips. Bounds are narrowed only for the single-chip case — for
        multi-chip grants the physical grid (e.g. v4's 2x2x1) must stay the
        default or libtpu rejects the topology (matches the reference)."""
        env["TPU_VISIBLE_CHIPS"] = ",".join(ids)
        if len(ids) == 1:
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
            env["TPU_PROCESS_BOUNDS"] = "1,1,1"
