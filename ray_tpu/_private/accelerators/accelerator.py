"""Accelerator manager interface.

Reference analog: ``python/ray/_private/accelerators/accelerator.py``
(AcceleratorManager ABC: autodetection, visibility env vars, extra
resources/labels per node). Managers are consulted at node start to fill in
resource counts and at worker launch to scope device visibility.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class AcceleratorManager(ABC):
    @staticmethod
    @abstractmethod
    def get_resource_name() -> str:
        """Scheduler resource name, e.g. "TPU"."""

    @staticmethod
    @abstractmethod
    def get_current_node_num_accelerators() -> int:
        """How many accelerator chips this node exposes (0 if none)."""

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        return None

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Extra resources to advertise (e.g. slice-head markers)."""
        return {}

    @staticmethod
    def get_current_node_labels() -> Dict[str, str]:
        return {}

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> Optional[str]:
        """Env var that scopes chip visibility for a worker process."""
        return None

    @staticmethod
    def set_visible_accelerators(ids: List[str], env: Dict[str, str]):
        """Write the visibility env var into ``env`` (in place)."""


_REGISTRY: List[type] = []


def register_accelerator_manager(cls: type):
    if cls not in _REGISTRY:
        _REGISTRY.append(cls)
    return cls


def all_accelerator_managers() -> List[type]:
    # populate defaults lazily to avoid import cycles
    from ray_tpu._private.accelerators import tpu  # noqa: F401

    return list(_REGISTRY)


def detect_node_accelerators() -> Dict[str, float]:
    """Aggregate resources contributed by every detected accelerator."""
    out: Dict[str, float] = {}
    for mgr in all_accelerator_managers():
        n = mgr.get_current_node_num_accelerators()
        if n > 0:
            out[mgr.get_resource_name()] = float(n)
            out.update(mgr.get_current_node_additional_resources())
    return out


def detect_node_labels() -> Dict[str, str]:
    out: Dict[str, str] = {}
    for mgr in all_accelerator_managers():
        if mgr.get_current_node_num_accelerators() > 0:
            out.update(mgr.get_current_node_labels())
    return out
