from ray_tpu._private.accelerators.accelerator import (
    AcceleratorManager,
    all_accelerator_managers,
    detect_node_accelerators,
    detect_node_labels,
    register_accelerator_manager,
)
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

__all__ = [
    "AcceleratorManager",
    "TPUAcceleratorManager",
    "all_accelerator_managers",
    "detect_node_accelerators",
    "detect_node_labels",
    "register_accelerator_manager",
]
