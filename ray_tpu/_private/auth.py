"""Cluster auth token helpers (reference: ``src/ray/rpc/authentication/``).

One place for the two security-critical behaviors every entrypoint needs —
the call sites (init, head_main, CLI, launcher) must not hand-roll them:

- ``ensure_cluster_token()``: mint a token unless one is present OR the
  operator explicitly opted out with ``RT_AUTH_TOKEN=`` (empty-but-set).
- ``adopt_token(info)``: take the token from a head's address/info dict
  when this process has none.

Both return True when they changed the environment, so callers that own a
cluster lifetime (ray_tpu.init) can undo the change at shutdown.
"""
from __future__ import annotations

import os

ENV = "RT_AUTH_TOKEN"


def ensure_cluster_token() -> bool:
    """Mint a cluster token into the env unless present or explicitly
    disabled. Returns True when this call minted one."""
    from ray_tpu._private.config import rt_config

    if ENV in os.environ or rt_config.auth_token:
        return False
    import secrets

    os.environ[ENV] = secrets.token_hex(16)
    return True


def adopt_token(info) -> bool:
    """Adopt ``info['auth_token']`` when this process has no token yet.
    Returns True when adopted."""
    tok = (info or {}).get("auth_token")
    if not tok or ENV in os.environ:
        return False
    os.environ[ENV] = tok
    return True


def redacted(info: dict) -> dict:
    """A copy safe to print/log: the token never reaches stdout (logs are
    routinely world-readable; the 0600 files are the distribution
    channel)."""
    if not info.get("auth_token"):
        return dict(info)
    return {**info, "auth_token": "<redacted>"}
