"""Memory pressure monitoring + OOM task rejection.

Reference analog: ``src/ray/common/threshold_memory_monitor.cc`` /
``pressure_memory_monitor.cc`` feeding the raylet's worker-killing policies
(``raylet/worker_killing_policy_*.h``): when a node crosses its memory
threshold, retriable tasks are killed/rejected so the node survives and the
owner retries elsewhere. Here the check runs at task admission in the worker
(process-per-host: the worker process IS the node).

cgroup v2 limits are honored when present (containers), else /proc/meminfo.
"""
from __future__ import annotations

import os
import time
from typing import Optional, Tuple

DEFAULT_THRESHOLD = 0.95
_CACHE_S = 0.5


def _rt_config():
    from ray_tpu._private.config import rt_config

    return rt_config


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            v = f.read().strip()
        return None if v == "max" else int(v)
    except (OSError, ValueError):
        return None


def _cgroup_reclaimable(stat_path: str) -> int:
    """Reclaimable page cache from memory.stat — counting it as used would
    flag I/O-heavy nodes as OOM. v1 usage is hierarchical, so prefer
    total_inactive_file (sums child cgroups) over the local counter."""
    local = total = None
    try:
        with open(stat_path) as f:
            for line in f:
                if line.startswith("total_inactive_file "):
                    total = int(line.split()[1])
                elif line.startswith("inactive_file "):
                    local = int(line.split()[1])
    except (OSError, ValueError):
        pass
    if total is not None:
        return total
    return local or 0


def get_memory_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) for this node's memory budget."""
    # cgroup v2 (container limit) first
    cur = _read_int("/sys/fs/cgroup/memory.current")
    lim = _read_int("/sys/fs/cgroup/memory.max")
    if cur is not None and lim is not None:
        cur -= _cgroup_reclaimable("/sys/fs/cgroup/memory.stat")
        return max(cur, 0), lim
    # cgroup v1
    cur = _read_int("/sys/fs/cgroup/memory/memory.usage_in_bytes")
    lim = _read_int("/sys/fs/cgroup/memory/memory.limit_in_bytes")
    if cur is not None and lim is not None and lim < (1 << 60):
        cur -= _cgroup_reclaimable("/sys/fs/cgroup/memory/memory.stat")
        return max(cur, 0), lim
    # host meminfo
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        pass
    if total is None:
        return 0, 1  # unknown: never report pressure
    used = total - (avail if avail is not None else total)
    return used, total


def used_ratio() -> float:
    """Current used/total fraction of this node's memory budget — the
    input the OOM admission rejection compares against its threshold,
    exported as ``rt_node_memory_used_ratio`` so pressure is observable
    BEFORE rejections fire (memtrack gauge tick)."""
    used, total = get_memory_usage()
    return used / total if total > 0 else 0.0


class MemoryMonitor:
    """Threshold monitor with a short result cache (admission is hot)."""

    def __init__(self, threshold: Optional[float] = None):
        if threshold is None:
            threshold = float(
                _rt_config().get("memory_threshold")
            )
        self.threshold = threshold
        self._last_check = 0.0
        self._last_result = False

    def is_pressing(self) -> bool:
        now = time.monotonic()
        if now - self._last_check < _CACHE_S:
            return self._last_result
        self._last_check = now
        used, total = get_memory_usage()
        self._last_result = total > 0 and used / total > self.threshold
        return self._last_result

    def usage_string(self) -> str:
        used, total = get_memory_usage()
        return (
            f"{used / (1 << 30):.2f}/{total / (1 << 30):.2f} GiB "
            f"({used / max(total, 1):.0%}, threshold "
            f"{self.threshold:.0%})"
        )
