"""Head service: cluster metadata, scheduling, actor management, pubsub, KV.

TPU-native analog of the reference GCS (``src/ray/gcs/gcs_server.h:97`` and its
managers: GcsNodeManager, GcsResourceManager, GcsActorManager,
GcsPlacementGroupManager, internal KV, function manager, pubsub). Design
differences, deliberate (SURVEY.md §7):

- **Process-per-host model**: a "node" here is one worker process (on a TPU pod
  each host runs exactly one multi-chip worker process), so the reference's
  raylet/worker split collapses into a single per-node service. The head
  schedules leases directly onto nodes — there is no per-node secondary
  scheduler in round 1.
- **Typed TPU resources**: nodes advertise {"CPU": n, "TPU": m, ...} plus
  labels (topology, slice name). Slice-aware gang placement lives in
  ``placement_group`` with STRICT_PACK ≈ one ICI slice.
- Transport is the framed-msgpack RPC in ``protocol.py`` (not gRPC); workers
  keep one bidirectional connection to the head, over which the head also
  pushes actor-creation requests and pubsub messages (reference's
  long-poll pubsub ``src/ray/pubsub/publisher.h`` becomes a plain push).
"""
from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from collections import OrderedDict, defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import faultpoints, flight, protocol
from ray_tpu._private.asyncio_util import spawn_logged
from ray_tpu._private.ids import ActorID, NodeID, PlacementGroupID

logger = logging.getLogger(__name__)


@dataclass
class NodeInfo:
    node_id: str
    addr: Tuple[str, int]          # worker-service address for task push
    resources: Dict[str, float]    # total
    available: Dict[str, float]    # currently available
    labels: Dict[str, str] = field(default_factory=dict)
    conn: Optional[protocol.Connection] = None  # head<->node control conn
    alive: bool = True
    start_time: float = field(default_factory=time.time)
    # Registration epoch: a stale close event from a connection this node
    # already replaced (re-register after a blip) must not kill the node.
    epoch: int = 0
    # Warm worker pool: a standby node is fully registered (process up,
    # connected, rings attachable) but invisible to the scheduler until
    # activated — the instant-capacity reserve rt_config.warm_workers
    # preforks (reference: prestarted idle workers in worker_pool.cc).
    standby: bool = False

    def to_public(self) -> dict:
        return {
            "node_id": self.node_id,
            "addr": list(self.addr),
            "resources": dict(self.resources),
            "available": dict(self.available),
            "labels": dict(self.labels),
            "alive": self.alive,
            "standby": self.standby,
        }


@dataclass
class ActorInfo:
    actor_id: str
    name: Optional[str]
    namespace: str
    state: str                     # PENDING | ALIVE | RESTARTING | DEAD
    node_id: Optional[str]
    addr: Optional[Tuple[str, int]]
    resources: Dict[str, float]
    max_restarts: int
    restarts_used: int = 0
    creation_frames: Optional[List[bytes]] = None  # replayed on restart
    death_reason: str = ""
    class_name: str = ""
    pg_id: Optional[str] = None
    bundle_index: int = -1
    detached: bool = False  # lifetime="detached": survives its owner
    # method name -> declared num_returns (@method(num_returns=N)); rides
    # the actor table so get_actor() handles honor declarations too.
    method_meta: Dict[str, int] = field(default_factory=dict)

    def to_public(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "name": self.name,
            "namespace": self.namespace,
            "state": self.state,
            "node_id": self.node_id,
            "addr": list(self.addr) if self.addr else None,
            "class_name": self.class_name,
            "restarts_used": self.restarts_used,
            "death_reason": self.death_reason,
            "method_meta": dict(self.method_meta),
        }


@dataclass
class PlacementGroupInfo:
    pg_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    state: str                     # PENDING | CREATED | REMOVED
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    name: str = ""

    def to_public(self) -> dict:
        return {
            "placement_group_id": self.pg_id,
            "name": self.name,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "state": self.state,
            "bundle_nodes": self.bundle_nodes,
        }


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


def _acquire(avail: Dict[str, float], need: Dict[str, float]):
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def _release(avail: Dict[str, float], need: Dict[str, float]):
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) + v


class HeadService:
    """The cluster head. Runs inside the driver process's core event loop in
    round 1 (single head service; reference runs it as a separate gcs_server
    process — the RPC surface is identical so it can be split out later)."""

    def __init__(self):
        self.kv: Dict[str, Dict[str, bytes]] = defaultdict(dict)  # ns -> key -> val
        self.nodes: Dict[str, NodeInfo] = {}
        # Bounded tombstones for the state API: node ids are fresh per
        # registration, so without pruning both this dict and the native
        # scheduler's node vector grow forever under autoscaler churn
        # (reference: GcsNodeManager keeps a capped dead-node cache).
        self.dead_nodes: Dict[str, NodeInfo] = {}
        self._DEAD_NODE_CACHE = 256
        self.actors: Dict[str, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}  # (ns, name) -> actor_id
        self.pgs: Dict[str, PlacementGroupInfo] = {}
        # pg_id -> bundle_index -> remaining reserved resources on that node
        self.pg_reserved: Dict[str, List[Dict[str, float]]] = {}
        self.subscribers: Dict[str, List[protocol.Connection]] = defaultdict(list)
        self.object_dir: Dict[str, dict] = {}  # object hex -> shm layout metadata
        self.server: Optional[protocol.RpcServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._pending_waiters: List[asyncio.Future] = []  # resource-wait futures
        self._last_reclaim = 0.0  # lease_reclaim publish rate limit
        # Monotonic serial per client connection — NOT id(conn): a closed
        # connection's id() can be reused by a new one before the scheduled
        # cleanup task runs, which would tear down the new owner's state.
        self._conn_serial = itertools.count(1)
        # conn-serial -> actor ids whose owner is that connection
        # (non-detached actors are destroyed when their owner disconnects)
        self._conn_actors: Dict[int, set] = {}
        # conn-serial -> outstanding lease grants [(node_id, resources,
        # strategy)]: a client killed mid-burst (SIGKILL, OOM) can never
        # send release_lease, so its grants are replayed on disconnect —
        # otherwise the head's view of node capacity leaks permanently
        # (reference: raylet returns a dead worker's leased resources via
        # the worker-failure path, ``cluster_lease_manager.cc``).
        self._conn_leases: Dict[int, list] = {}
        # Task-event ring for the state API: a bounded deque, consistent
        # with the flight recorder's ring semantics — append is O(1),
        # overflow drops the OLDEST event (a plain list trimmed with del
        # slicing memmoved the whole buffer on every overflow), and the
        # drop count is reported, never silent.
        self.task_events: deque = deque(maxlen=10_000)
        self._task_events_total = 0
        # Log plane: recent worker log lines per node (bounded ring), fed
        # by worker_logs notifies, served to `rt logs` + the dashboard.
        self.log_buffer: Dict[str, deque] = {}
        self._LOG_BUFFER_LINES = 10_000
        self.jobs: Dict[str, dict] = {}
        self._schedule_rr = 0  # round-robin cursor
        self._shutting_down = False
        self._death_tasks: set = set()  # in-flight _on_node_dead tasks
        # Unsatisfied lease demands, keyed by waiter id — the autoscaler's
        # scale-up signal (reference: GcsAutoscalerStateManager feeding
        # autoscaler v2 with pending resource demands).
        self.pending_demands: Dict[int, dict] = {}
        self.job_procs: Dict[str, object] = {}  # submission_id -> Popen
        self.worker_metrics: Dict[str, list] = {}  # worker -> metric snapshot
        # Correlation-id dedup for retried non-idempotent verbs (lease,
        # create_actor, create_pg): a retry after a DROPPED REPLY must
        # return the original outcome, not apply the verb twice — the
        # reference's reply-path failures are absorbed the same way by
        # server-side request dedup. Entries are (conn serial, reply) —
        # connection-scoped, since a disconnect rolls the outcome back —
        # in a bounded LRU; only successful replies are cached (a failed
        # attempt may legitimately succeed on retry).
        self._corr_replies: "OrderedDict[str, tuple]" = OrderedDict()
        self._CORR_CACHE = 1024
        self._task_state_counts: Dict[str, int] = {}  # FINISHED/FAILED/...
        # Native C++ scheduler (reference: the C++ ClusterResourceScheduler,
        # ``raylet/scheduling/cluster_resource_scheduler.cc:155``): fixed-point
        # resource accounting + best-node policies in ray_tpu/native/src/sched.cc.
        # The NodeInfo.available dicts stay as a mirror for the state API and
        # autoscaler; scheduling decisions come from the native side when the
        # library is buildable (RT_NATIVE_SCHED=0 forces the Python fallback).
        self._nsched = None
        from ray_tpu._private.config import rt_config

        if rt_config.native_sched:
            try:
                from ray_tpu.native import sched as _native_sched

                self._nsched = _native_sched.create()
            except Exception:
                logger.exception("native scheduler unavailable; Python fallback")

    # ------------------------------------------------------------------ setup

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self.server = protocol.RpcServer(self._handle, host, port)
        self.addr = await self.server.start()
        logger.info("head service listening on %s", self.addr)
        # Structured export-event pipeline (reference: RayEventRecorder →
        # aggregator agent): lifecycle transitions below emit typed events
        # persisted as JSON-lines in the session dir.
        try:
            from ray_tpu.util.events import EventRecorder

            session_dir = os.environ.get(
                "RT_SESSION_DIR", f"/tmp/ray_tpu/session_p{self.addr[1]}"
            )
            self.events = EventRecorder(
                path=os.path.join(session_dir, "events", "events.jsonl")
            )
        except Exception:
            logger.exception("export-event recorder unavailable")
            self.events = None
        return self.addr

    def _emit_event(self, source_type: str, event_type: str,
                    entity_id: str, message: str = "", **attrs):
        if getattr(self, "events", None) is None:
            return
        try:
            self.events.emit(
                source_type, event_type, entity_id, message, **attrs
            )
        except Exception as e:
            # Observability must never take down the control plane, but a
            # persistently failing exporter should be visible in debug logs.
            logger.debug("export-event emit (%s/%s) failed: %s",
                         source_type, event_type, e)

    # WAL: durable-table mutations (KV, jobs) append a record BEFORE the
    # RPC reply, closing the between-snapshots loss window (reference:
    # redis_store_client.cc — per-mutation durability, not timer-based).
    def attach_wal(self, path_prefix: str):
        from ray_tpu._private.wal import WalWriter

        self.wal = WalWriter(path_prefix)
        return self.wal

    def _wal_append(self, op: dict):
        wal = getattr(self, "wal", None)
        if wal is None:
            return
        try:
            wal.append(op)
            wal.schedule_fsync(asyncio.get_running_loop())
        except Exception:
            logger.exception("WAL append failed (durability degraded)")

    def replay_wal(self, path_prefix: str) -> int:
        """Apply surviving WAL records over restored snapshot state.
        Idempotent: puts overwrite, deletes are best-effort, job records
        merge like restore() (running work is terminal after a restart)."""
        from ray_tpu._private.wal import replay_all

        n = 0
        for op in replay_all(path_prefix):
            kind = op.get("op")
            if kind == "kv_put":
                self.kv[op["ns"]][op["key"]] = op["val"]
            elif kind == "kv_del":
                self.kv[op["ns"]].pop(op["key"], None)
            elif kind == "kv_del_prefix":
                ns = self.kv[op["ns"]]
                for k in [k for k in ns if k.startswith(op["prefix"])]:
                    ns.pop(k, None)
            elif kind == "job":
                info = dict(op["job"])
                if info.get("status") in ("RUNNING", "STOPPING", "PENDING"):
                    info["status"] = "FAILED"
                    info.setdefault("end_time", time.time())
                if info.get("state") == "RUNNING":
                    info["state"] = "DEAD"
                    info.setdefault("end_time", time.time())
                self.jobs[info["job_id"]] = {
                    **self.jobs.get(info["job_id"], {}), **info
                }
            n += 1
        return n

    async def close(self):
        self._shutting_down = True
        if self.server:
            await self.server.close()
        # Settle in-flight node-death handlers so none outlive the loop.
        if self._death_tasks:
            await asyncio.gather(
                *list(self._death_tasks), return_exceptions=True
            )
        if getattr(self, "events", None) is not None:
            try:
                self.events.close()
            except Exception:
                pass

    # -------------------------------------------------------- persistence
    # Reference analog: GCS fault tolerance via Redis-backed store +
    # GcsInitData replay (``gcs/store_client/redis_store_client.cc``,
    # ``gcs_init_data.cc``): durable metadata survives a head restart.
    # Round-1 scope: the durable tables are the KV (function table, train
    # rendezvous, user data) and job records; live process state (nodes,
    # actors) re-registers on reconnect.

    def snapshot(self) -> bytes:
        import pickle

        jobs = {
            jid: {k: v for k, v in info.items()}
            for jid, info in self.jobs.items()
        }
        return pickle.dumps({
            "version": 1,
            # The listen address rides the snapshot so a restarted head can
            # REBIND the same port — live nodes/drivers reconnect to the
            # address they already hold (reference: GCS restarts behind a
            # stable address and raylets reconnect, gcs_init_data replay).
            "addr": list(self.addr) if self.addr else None,
            "kv": {ns: dict(kvs) for ns, kvs in self.kv.items()},
            "jobs": jobs,
        })

    def restore(self, blob: bytes):
        import pickle

        state = pickle.loads(blob)
        # Surfaced for head_main: rebind this port so live clients rejoin.
        self.restored_addr = (
            tuple(state["addr"]) if state.get("addr") else None
        )
        for ns, kvs in state.get("kv", {}).items():
            self.kv[ns].update(kvs)
        for jid, info in state.get("jobs", {}).items():
            info = dict(info)
            # processes did not survive the head: running work is terminal.
            # Submission jobs track "status"; driver-registered jobs "state".
            if info.get("status") in ("RUNNING", "STOPPING", "PENDING"):
                info["status"] = "FAILED"
                info.setdefault("end_time", time.time())
            if info.get("state") == "RUNNING":
                info["state"] = "DEAD"
                info.setdefault("end_time", time.time())
            self.jobs.setdefault(jid, info)

    @staticmethod
    def write_snapshot(path: str, blob: bytes):
        """Atomic fsync'd write; safe to run off the event loop (the blob
        was produced on-loop, so no handler races the tables)."""
        import os
        import tempfile

        d = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".head_state_")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())  # replace() must publish complete bytes
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def save_to_file(self, path: str):
        self.write_snapshot(path, self.snapshot())

    def load_from_file(self, path: str) -> bool:
        try:
            with open(path, "rb") as f:
                self.restore(f.read())
            return True
        except FileNotFoundError:
            return False
        except Exception:
            # A corrupt/truncated snapshot must not crash-loop the head —
            # starting empty beats never starting.
            logger.exception("head state %s unreadable; starting fresh", path)
            return False

    # ------------------------------------------------------------- dispatcher

    async def _handle(self, method, header, frames, conn):
        if not flight.ENABLED:
            return await self._handle_inner(method, header, frames, conn)
        # Per-verb dispatch span with queue wait (message arrival → handler
        # start, i.e. head event-loop backlog) recorded separately from
        # handler time — the breakdown the two ROADMAP perf items need.
        t0 = time.monotonic()
        arr = header.get("_fr") or t0
        try:
            out = await self._handle_inner(method, header, frames, conn)
        except faultpoints.DropReply:
            flight.record_dispatch(f"gcs.{method}", "head", header, arr,
                                   t0, 0, "drop_reply")
            raise
        except BaseException as e:
            flight.record_dispatch(f"gcs.{method}", "head", header, arr,
                                   t0, 0, f"error:{type(e).__name__}")
            raise
        flight.record_dispatch(f"gcs.{method}", "head", header, arr, t0)
        return out

    async def _handle_inner(self, method, header, frames, conn):
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise protocol.RpcError(f"unknown head rpc {method}")
        corr = header.get("corr")
        fut = None
        if corr is not None:
            # Dedup entries are CONNECTION-scoped: a disconnect replays the
            # ledger (leases returned, owned actors reaped), so a retry
            # arriving on a NEW connection must re-execute the verb — the
            # cached outcome describes state the disconnect already rolled
            # back, and replaying e.g. grants would hand out capacity the
            # head no longer tracks.
            serial = self._conn_key(conn)
            cached = self._corr_replies.get(corr)
            if cached is not None and cached[0] != serial:
                self._corr_replies.pop(corr, None)
                cached = None
            if cached is not None:
                payload = cached[1]
                if isinstance(payload, asyncio.Future):
                    # Retry of a request the head is STILL executing (the
                    # client's deadline beat a slow verb): attach to the
                    # in-flight execution instead of double-applying it.
                    return await asyncio.shield(payload)
                # Retry of a request whose reply we already produced (it
                # was dropped in flight): replay the original outcome.
                return payload
            fut = asyncio.get_running_loop().create_future()
            # A failed attempt is retried for real, but its exception must
            # count as retrieved for any attached retry (and the default
            # handler's never-retrieved warning).
            fut.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None
            )
            self._corr_replies[corr] = (serial, fut)
        act = None
        if faultpoints.ACTIVE:
            # error fails the verb BEFORE it runs (code="unavailable" so
            # retryable clients re-issue); drop is remembered and applied
            # AFTER — the applied-but-unacknowledged partial failure.
            try:
                act = await faultpoints.async_fire(f"gcs.dispatch.{method}")
            except BaseException as e:
                if fut is not None:
                    self._corr_replies.pop(corr, None)
                    fut.set_exception(e)
                raise
        try:
            out = await fn(header, frames, conn)
        except BaseException as e:
            if fut is not None:
                # Real failure: drop the entry so a retry re-executes.
                self._corr_replies.pop(corr, None)
                fut.set_exception(e)
            raise
        if fut is not None:
            self._corr_replies[corr] = (serial, out)
            fut.set_result(out)
            # Evict oldest COMPLETED entries only: popping an in-flight
            # future would let that request's retry double-execute — the
            # overshoot is bounded by the number of concurrent corr verbs.
            while len(self._corr_replies) > self._CORR_CACHE:
                k, v = next(iter(self._corr_replies.items()))
                if isinstance(v[1], asyncio.Future):
                    break
                self._corr_replies.pop(k, None)
        if act == "drop":
            raise faultpoints.DropReply()
        return out

    # ------------------------------------------------------------------- kv

    async def rpc_kv_put(self, h, frames, conn):
        ns = h.get("ns", "")
        val = frames[0] if frames else b""
        self.kv[ns][h["key"]] = val
        self._wal_append({"op": "kv_put", "ns": ns, "key": h["key"],
                          "val": val})
        return {}, []

    async def rpc_kv_get(self, h, frames, conn):
        val = self.kv[h.get("ns", "")].get(h["key"])
        return {"found": val is not None}, ([val] if val is not None else [])

    async def rpc_kv_get_batch(self, h, frames, conn):
        """Multi-key kv_get in one round trip. Workers coalesce concurrent
        function-table misses into one of these, so a burst that lands on
        a fresh worker costs O(unique functions) head RPCs, not O(tasks)
        (reference shape: MGET batching in the GCS table client). Reply
        frames carry only the found values, in key order."""
        ns = self.kv[h.get("ns", "")]
        found = []
        vals = []
        for k in h.get("keys", ()):
            v = ns.get(k)
            found.append(v is not None)
            if v is not None:
                vals.append(v)
        return {"found": found}, vals

    async def rpc_kv_del(self, h, frames, conn):
        existed = self.kv[h.get("ns", "")].pop(h["key"], None) is not None
        if existed:
            self._wal_append({"op": "kv_del", "ns": h.get("ns", ""),
                              "key": h["key"]})
        return {"deleted": existed}, []

    async def rpc_kv_del_prefix(self, h, frames, conn):
        ns = self.kv[h.get("ns", "")]
        doomed = [k for k in ns if k.startswith(h.get("prefix", ""))]
        for k in doomed:
            ns.pop(k, None)
        if not ns:
            self.kv.pop(h.get("ns", ""), None)
        if doomed:
            self._wal_append({"op": "kv_del_prefix", "ns": h.get("ns", ""),
                              "prefix": h.get("prefix", "")})
        return {"deleted": len(doomed)}, []

    async def rpc_kv_keys(self, h, frames, conn):
        prefix = h.get("prefix", "")
        keys = [k for k in self.kv[h.get("ns", "")] if k.startswith(prefix)]
        return {"keys": keys}, []

    async def rpc_kv_exists(self, h, frames, conn):
        return {"exists": h["key"] in self.kv[h.get("ns", "")]}, []

    # ------------------------------------------------------------------ nodes

    async def rpc_register_node(self, h, frames, conn):
        info = NodeInfo(
            node_id=h["node_id"],
            addr=tuple(h["addr"]),
            resources=dict(h["resources"]),
            available=dict(h["resources"]),
            # Label values are strings (as in the reference's label
            # selectors); stringify so the Python and native comparison
            # paths agree for non-string inputs.
            labels={k: str(v) for k, v in h.get("labels", {}).items()},
            conn=conn,
            standby=bool(h.get("standby")),
        )
        # Activation is sticky across re-registration: a blip + reconnect
        # of a node the head already activated (it may hold leases and
        # running tasks, which don't show up in hosted_actors) must not
        # fall back into the invisible standby set.
        prior = self.nodes.get(info.node_id)
        if info.standby and prior is not None and not prior.standby:
            info.standby = False
        self.nodes[info.node_id] = info
        # A fixed-id node (worker_main --node-id) may re-register after a
        # death: drop its tombstone or it would be listed both alive and
        # dead — and the autoscaler's dead_ids check would terminate the
        # healthy instance on every reconcile.
        self.dead_nodes.pop(info.node_id, None)
        # Standby (warm pool) nodes stay OUT of the scheduler until
        # activated; the native scheduler learns about them at activation.
        if self._nsched is not None and not info.standby:
            self._nsched.add_node(info.node_id, info.resources, info.labels)
        # Epoch guards the close handler: the OLD connection of a node that
        # just re-registered (blip + reconnect) must not tear down the NEW
        # registration when its queued close event finally runs.
        info.epoch = next(self._conn_serial)
        self._emit_event("NODE", "NODE_ALIVE", info.node_id,
                         addr=list(info.addr), resources=info.resources)
        conn.peer_info["node_id"] = info.node_id
        conn.on_close = self._make_node_close_handler(info.node_id, info.epoch)
        # Live rejoin after a head restart: the node re-reports the actors
        # it is still hosting; adopt them as ALIVE so handles (and names)
        # keep resolving. Owner tracking died with the old head — adopted
        # actors behave as detached until explicitly killed (reference:
        # GcsInitData replay rebuilding the actor table).
        for a in h.get("hosted_actors", ()):
            existing = self.actors.get(a["actor_id"])
            if existing is not None and existing.state != "DEAD":
                # Same-head re-register (connection blip): the fresh
                # NodeInfo reset availability, so re-deduct what this
                # still-ALIVE actor occupies (PG-backed actors draw from
                # their bundle reservation instead).
                if existing.node_id == info.node_id and not existing.pg_id \
                        and existing.resources:
                    self._node_acquire(info, existing.resources)
                continue
            ainfo = ActorInfo(
                actor_id=a["actor_id"],
                name=a.get("name"),
                namespace=a.get("namespace", "default"),
                state="ALIVE",
                node_id=info.node_id,
                addr=tuple(h["addr"]),
                resources={
                    k: float(v) for k, v in (a.get("resources") or {}).items()
                },
                max_restarts=0,
                creation_frames=[],
                class_name=a.get("class_name", ""),
                detached=True,
                method_meta=dict(a.get("method_meta") or {}),
            )
            self.actors[a["actor_id"]] = ainfo
            if ainfo.name:
                self.named_actors[(ainfo.namespace, ainfo.name)] = (
                    ainfo.actor_id
                )
            # The adopted actor still occupies its slot on the node.
            if ainfo.resources:
                self._node_acquire(info, ainfo.resources)
        # PG bundles reserved on this node also still occupy capacity —
        # re-deduct them from the fresh NodeInfo (same-head re-register;
        # a restarted head has no pgs and this is a no-op).
        for pg_id, pg in self.pgs.items():
            if pg.state != "CREATED":
                continue
            for i, nid in enumerate(pg.bundle_nodes):
                if nid == info.node_id:
                    self._node_acquire(info, pg.bundles[i])
        # Likewise plain leases other (still-connected) clients hold here.
        for ledger in self._conn_leases.values():
            for nid, need, strategy in ledger:
                if nid == info.node_id and not (strategy or {}).get("pg_id"):
                    self._node_acquire(info, need)
        self._wake_waiters()
        self.publish("nodes", {"event": "node_added", "node": info.to_public()})
        return {"ok": True}, []

    def _make_node_close_handler(self, node_id, epoch: int = 0):
        loop = asyncio.get_running_loop()

        def _spawn():
            # During shutdown every node connection closes at once; spawning
            # death handlers then races loop.stop (tasks created but never
            # run → "coroutine was never awaited" warnings) and does no
            # useful work — the cluster is going away.
            if loop.is_closed() or self._shutting_down:
                return
            coro = self._on_node_dead(node_id, epoch=epoch)
            try:
                t = loop.create_task(coro)
            except RuntimeError:
                coro.close()  # loop torn down between check and create
            else:
                self._death_tasks.add(t)
                t.add_done_callback(self._death_tasks.discard)

        def _on_close(conn):
            if not loop.is_closed() and not self._shutting_down:
                try:
                    loop.call_soon_threadsafe(_spawn)
                except RuntimeError:
                    pass  # loop torn down concurrently
        return _on_close

    async def _on_node_dead(self, node_id: str, reason: str = "connection lost",
                            epoch: int = 0):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        if epoch and getattr(info, "epoch", 0) != epoch:
            # Stale close event from a connection the node already replaced
            # by re-registering: the live registration stays up.
            return
        info.alive = False
        if self._nsched is not None:
            self._nsched.set_alive(node_id, False)
        # Planned departures (drain_node before a deliberate teardown,
        # cluster shutdown) are expected: warning-level "node dead" lines
        # for them read as failures in bench/CI tails and mask real ones.
        log = (
            logger.debug
            if getattr(self, "_shutting_down", False) or reason == "drained"
            else logger.warning
        )
        log("node %s dead: %s", node_id[:8], reason)
        self._emit_event("NODE", "NODE_DEAD", node_id, message=reason)
        self.publish("nodes", {"event": "node_dead", "node_id": node_id})
        # Log plane: keep a post-mortem tail for the dead node but shrink
        # its ring (a full 10k-line deque per dead node would grow the head
        # without bound under autoscaler churn), and cap how many dead-node
        # tails are retained at all.
        buf = self.log_buffer.get(node_id)
        if buf is not None and len(buf) > 500:
            self.log_buffer[node_id] = deque(
                itertools.islice(buf, len(buf) - 500, None), maxlen=500
            )
        dead_with_logs = [
            nid for nid in self.log_buffer
            if nid not in self.nodes or not self.nodes[nid].alive
        ]
        for nid in dead_with_logs[: max(len(dead_with_logs) - 32, 0)]:
            self.log_buffer.pop(nid, None)
        # Fail/restart actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in ("ALIVE", "PENDING"):
                await self._on_actor_dead(actor, f"node {node_id[:8]} died")
        # Release PG reservations on that node.
        for pg in self.pgs.values():
            for i, nid in enumerate(pg.bundle_nodes):
                if nid == node_id:
                    pg.bundle_nodes[i] = None
        # Drop the dead node's metric series.
        self.worker_metrics = {
            wid: rec for wid, rec in self.worker_metrics.items()
            if rec.get("node_id") != node_id
        }
        # Actors/PG reservations are drained above and lease releases tolerate
        # a missing node, so retire the node now: scheduler state goes away
        # entirely (best_node scans linearly), the public record moves to the
        # bounded tombstone cache.
        if self._nsched is not None:
            self._nsched.remove_node(node_id)
        info = self.nodes.pop(node_id, None)
        if info is not None:
            info.conn = None
            self.dead_nodes[node_id] = info
            while len(self.dead_nodes) > self._DEAD_NODE_CACHE:
                self.dead_nodes.pop(next(iter(self.dead_nodes)))

    async def rpc_cluster_stacks(self, h, frames, conn):
        """Fan out all-thread stack dumps to every alive node (reference:
        ``ray stack`` + the reporter agent's py-spy hooks; workers answer
        natively from sys._current_frames — util/debug.py)."""
        alive = [
            n for n in self.nodes.values() if n.alive and n.conn is not None
        ]

        async def one(node):
            try:
                hh, _ = await asyncio.wait_for(
                    node.conn.call("dump_stacks", {}), timeout=10
                )
                return node.node_id, hh.get("stacks", "")
            except Exception as e:
                return (
                    node.node_id,
                    f"<unavailable: {type(e).__name__}: {e}>",
                )

        # concurrent fan-out: a partially-hung cluster (the very case a
        # stack tool exists for) costs one timeout, not one per dead node
        results = await asyncio.gather(*(one(n) for n in alive))
        return {"nodes": dict(results)}, []

    async def rpc_flight_snapshot(self, h, frames, conn):
        """Fan ``flight_drain`` out to every alive node and return the
        clock-annotated per-process snapshots (this process's ring first).

        Each node snapshot gets an ``offset``: seconds to add to its wall
        times to land on the head's clock, estimated Cristian-style from
        the drain RPC midpoint vs. the node's reported wall clock — so the
        merged trace (flight.merge_snapshots) is head-clock aligned."""
        drain = bool(h.get("drain", True))
        local = flight.drain() if drain else flight.snapshot()
        local["offset"] = 0.0
        # Drain every connected PROCESS, not just registered nodes:
        # remote drivers (init(address=...)) hold the submission-side
        # spans — exactly the costs this instrument measures. Every peer
        # with a CoreWorker answers flight_drain; tool clients (sync CLI,
        # dashboard) reply without a "flight" payload and are skipped.
        targets = {}
        for n in self.nodes.values():
            if n.alive and n.conn is not None:
                targets[id(n.conn)] = (n.conn, n.node_id[:8])
        for conn in (self.server.connections if self.server else ()):
            targets.setdefault(id(conn), (conn, None))

        async def one(conn, label):
            t_send = time.time()
            try:
                hh, _ = await asyncio.wait_for(
                    conn.call("flight_drain", {"drain": drain}),
                    timeout=10,
                )
            except (asyncio.TimeoutError, protocol.RpcError,
                    protocol.ConnectionLost, OSError) as e:
                logger.debug("flight_drain from %s failed: %s",
                             label or conn.name, e)
                return None
            t_recv = time.time()
            s = hh.get("flight")
            if not s:
                return None
            s["offset"] = (t_send + t_recv) / 2.0 - float(
                s.get("now") or t_recv
            )
            if label:
                s.setdefault("proc", label)
            elif s.get("proc") == "driver":
                # Remote drivers: keep their track groups distinct.
                s["proc"] = f"driver-{s.get('pid')}"
            return s

        results = await asyncio.gather(
            *(one(conn, label) for conn, label in targets.values())
        )
        # One snapshot per PROCESS: a peer reachable over two connections
        # answers the drain once with events, once empty — keep the
        # fuller reply (and never this process twice). Keyed by the
        # recorder's process token, not the OS pid: pids collide across
        # hosts.
        def skey(s):
            return s.get("token") or ("pid", s.get("pid"))

        by_proc = {skey(local): local}
        for s in results:
            if not s:
                continue
            prev = by_proc.get(skey(s))
            if prev is None or len(s.get("events") or ()) > len(
                prev.get("events") or ()
            ):
                by_proc[skey(s)] = s
        return {"snapshots": list(by_proc.values()),
                "enabled": flight.ENABLED}, []

    async def rpc_node_debug(self, h, frames, conn):
        """Relay a debug RPC (memory_profile, dump_stacks) to one node."""
        node = self.nodes.get(h.get("node_id") or "")
        if node is None or not node.alive or node.conn is None:
            raise protocol.RpcError(f"node {h.get('node_id')!r} unavailable")
        method = h.get("method")
        if method not in ("memory_profile", "dump_stacks", "cpu_profile",
                          "xla_profile"):
            raise protocol.RpcError(f"node_debug: unsupported {method!r}")
        fwd = {
            k: h[k]
            for k in ("action", "top", "duration_s", "hz", "logdir")
            if k in h
        }
        hh, _ = await asyncio.wait_for(
            node.conn.call(method, fwd),
            timeout=max(float(h.get("duration_s") or 0) + 30, 30),
        )
        # strip the forwarded reply's RPC envelope fields
        return {k: v for k, v in hh.items() if k not in ("i", "r")}, []

    async def rpc_drain_node(self, h, frames, conn):
        await self._on_node_dead(h["node_id"], "drained")
        return {}, []

    def _public_nodes(self) -> list:
        """Alive nodes plus dead tombstones — the state API and the
        autoscaler (phantom-instance reclaim) both need the dead ones."""
        return [
            n.to_public()
            for n in (*self.nodes.values(), *self.dead_nodes.values())
        ]

    async def rpc_get_nodes(self, h, frames, conn):
        return {"nodes": self._public_nodes()}, []

    # -------------------------------------------------------------- scheduler

    def _node_acquire(self, node: NodeInfo, need: Dict[str, float]):
        """Node-level resource acquisition: Python mirror + native scheduler."""
        _acquire(node.available, need)
        if self._nsched is not None:
            self._nsched.acquire(node.node_id, need)

    def _node_release(self, node: NodeInfo, need: Dict[str, float]):
        _release(node.available, need)
        # Invariant clamp: a release the (possibly restarted) head never
        # granted — e.g. a worker finishing a pre-restart busy lease — must
        # not inflate availability past the node's physical total.
        for k, total in node.resources.items():
            if node.available.get(k, 0.0) > total:
                node.available[k] = total
        if self._nsched is not None:
            self._nsched.release(node.node_id, need)

    def _schedulable_nodes(self, need, labels=None, node_id=None):
        out = []
        for n in self.nodes.values():
            if not n.alive or n.standby:
                continue
            if node_id is not None and n.node_id != node_id:
                continue
            if labels and any(
                n.labels.get(k) != str(v) for k, v in labels.items()
            ):
                continue
            out.append(n)
        return out

    def _pick_node(self, need: Dict[str, float], strategy: dict,
                   avoid=None) -> Optional[NodeInfo]:
        """Hybrid policy (reference: ``scheduling/policy/hybrid_scheduling_policy.cc``):
        pack onto earliest nodes with room, spread when strategy requests it.
        ``avoid``: soft blocklist (e.g. memory-pressured nodes) — used only
        when an alternative fits."""
        pg_id = strategy.get("pg_id")
        if pg_id:
            return self._pick_pg_node(need, pg_id, strategy.get("bundle_index", -1))
        if self._nsched is not None:
            node_id = self._nsched.best_node(
                need,
                spread=bool(strategy.get("spread")),
                affinity_node=strategy.get("node_id"),
                labels=strategy.get("labels"),
                avoid=avoid or (),
            )
            if node_id:
                return self.nodes.get(node_id)
            return self._activate_standby(need, strategy)
        cands = self._schedulable_nodes(
            need, strategy.get("labels"), strategy.get("node_id")
        )
        fitting = [n for n in cands if _fits(n.available, need)]
        if avoid:
            preferred = [n for n in fitting if n.node_id not in avoid]
            if preferred:
                fitting = preferred
        if not fitting:
            return self._activate_standby(need, strategy)
        if strategy.get("spread"):
            self._schedule_rr += 1
            return fitting[self._schedule_rr % len(fitting)]
        # pack: most-utilized first for binpacking; stable by id
        fitting.sort(key=lambda n: (sum(n.available.values()), n.node_id))
        return fitting[0]

    def _pick_pg_node(self, need, pg_id, bundle_index) -> Optional[NodeInfo]:
        pg = self.pgs.get(pg_id)
        if pg is None or pg.state != "CREATED":
            return None
        indices = [bundle_index] if bundle_index >= 0 else range(len(pg.bundles))
        for i in indices:
            node_id = pg.bundle_nodes[i]
            if node_id is None:
                continue
            node = self.nodes.get(node_id)
            reserved = self.pg_reserved[pg_id][i]
            if node and node.alive and _fits(reserved, need):
                _acquire(reserved, need)
                return node
        return None

    def _activate_standby(self, need, strategy) -> Optional["NodeInfo"]:
        """Warm worker pool: when demand outgrows schedulable capacity,
        flip a fitting STANDBY node into the active set and hand it
        straight to the caller — the first task/actor push lands on an
        already-initialized process instead of waiting out a cold node
        spawn. No-op (None) when the pool is empty."""
        labels = (strategy or {}).get("labels")
        want_id = (strategy or {}).get("node_id")
        for n in self.nodes.values():
            if not n.standby or not n.alive:
                continue
            if want_id is not None and n.node_id != want_id:
                continue
            if labels and any(
                n.labels.get(k) != str(v) for k, v in labels.items()
            ):
                continue
            if not _fits(n.available, need):
                continue
            self._activate_node(n)
            # Taskpath plane: the grant built from this pick is tagged
            # "warm" so the driver can name a queued task's wait
            # warm-pool-hit instead of lease-wait (popped by rpc_lease).
            n.__dict__["_rt_warm_grant"] = True
            return n
        return None

    def _activate_node(self, n: "NodeInfo"):
        """Standby -> schedulable: register with the native scheduler,
        announce the capacity, and wake anyone blocked on placement."""
        n.standby = False
        if self._nsched is not None:
            self._nsched.add_node(n.node_id, n.resources, n.labels)
        self._emit_event("NODE", "NODE_ACTIVATED", n.node_id,
                         resources=n.resources)
        self.publish("nodes", {"event": "node_added", "node": n.to_public()})
        self._wake_waiters()

    async def rpc_activate_node(self, h, frames, conn):
        """Explicitly activate a standby node (LocalCluster.add_node's
        warm fast path). Idempotent: activating an active node is ok."""
        n = self.nodes.get(h.get("node_id") or "")
        if n is None or not n.alive:
            return {"found": False}, []
        if n.standby:
            self._activate_node(n)
        return {"found": True, "node_id": n.node_id}, []

    async def rpc_lease(self, h, frames, conn):
        """Grant up to ``count`` leases for ``resources`` (one task slot each).

        Reference shape: NormalTaskSubmitter's RequestWorkerLease
        (``task_submission/normal_task_submitter.h:271``) against the raylet's
        ClusterLeaseManager; here the head is the single lease authority.
        """
        if faultpoints.ACTIVE:
            # Before ANY acquisition: an injected grant failure must leave
            # the availability ledger untouched.
            await faultpoints.async_fire("gcs.lease.grant")
        need = {k: float(v) for k, v in h.get("resources", {}).items()}
        strategy = h.get("strategy", {})
        count = h.get("count", 1)
        timeout = h.get("timeout", 30.0)
        avoid = set(h.get("avoid") or ())
        grants = []
        deadline = time.monotonic() + timeout
        while len(grants) < count:
            if getattr(conn, "_rt_conn_dead", False):
                break  # requester died while waiting; don't grant to a ghost
            node = self._pick_node(need, strategy, avoid)
            if node is not None:
                if not strategy.get("pg_id"):
                    self._node_acquire(node, need)
                grant = {"node_id": node.node_id, "addr": list(node.addr)}
                if node.__dict__.pop("_rt_warm_grant", False):
                    grant["warm"] = 1
                grants.append(grant)
                self._track_conn_lease(conn, node.node_id, need, strategy)
                continue
            if grants:
                break  # return partial grants rather than blocking
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            # Ask workers to return cached idle leases before blocking:
            # a recent task burst can leave every CPU pinned by slots that
            # are idle but inside their reaper window.
            self._maybe_reclaim_leases([need])
            fut = asyncio.get_running_loop().create_future()
            self._pending_waiters.append(fut)
            self.pending_demands[id(fut)] = {
                "resources": dict(need),
                "count": count - len(grants),  # bundles still unsatisfied
                "since": time.time(),
            }
            try:
                await asyncio.wait_for(fut, timeout=min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
            finally:
                self.pending_demands.pop(id(fut), None)
        return {"grants": grants, "resources": need}, []

    async def rpc_release_lease(self, h, frames, conn):
        need = {k: float(v) for k, v in h.get("resources", {}).items()}
        strategy = h.get("strategy", {})
        self._untrack_conn_lease(conn, h.get("node_id"), need, strategy)
        pg_id = strategy.get("pg_id")
        if pg_id:
            pg = self.pgs.get(pg_id)
            reserved = self.pg_reserved.get(pg_id)
            if pg is not None and reserved is not None:
                # return to the bundle's reservation
                idx = strategy.get("bundle_index", -1)
                node_id = h.get("node_id")
                indices = [idx] if idx >= 0 else range(len(pg.bundles))
                for i in indices:
                    if pg.bundle_nodes[i] == node_id:
                        _release(reserved[i], need)
                        break
            elif pg is not None:
                # PG was removed while this lease was outstanding: the bundle
                # reservation is gone, so the loaned resources go straight
                # back to the node (remove_pg only returned the unloaned
                # remainder).
                node = self.nodes.get(h.get("node_id") or "")
                if node is not None and node.alive:
                    self._node_release(node, need)
        else:
            node = self.nodes.get(h["node_id"])
            if node is not None:
                self._node_release(node, need)
        self._wake_waiters()
        return {}, []

    def _maybe_reclaim_leases(self, needs: List[Dict[str, float]]):
        """Publish lease_reclaim only when it could actually help and at
        most ~4x/s: an infeasible request (bundle bigger than any node's
        TOTAL capacity) must not flush every worker's lease cache once per
        wait iteration for its whole timeout — that would disable the
        cache cluster-wide for concurrent workloads."""
        now = time.monotonic()
        if now - self._last_reclaim < 0.25:
            return
        alive = [n for n in self.nodes.values() if n.alive]
        for need in needs:
            if not any(
                all(n.resources.get(k, 0.0) >= v for k, v in need.items())
                for n in alive
            ):
                return  # can't fit even on an empty node: reclaim won't help
        self._last_reclaim = now
        self.publish("lease_reclaim", {})

    def _wake_waiters(self):
        waiters, self._pending_waiters = self._pending_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)
        # Freed resources may satisfy a placement group whose creation RPC
        # already returned PENDING; without this retry it would pend
        # forever even on an empty cluster.
        self._schedule_pending_pgs()

    # ----------------------------------------------------------------- actors

    async def rpc_create_actor(self, h, frames, conn):
        """Register + schedule an actor (reference: GcsActorManager
        ``HandleRegisterActor``/``HandleCreateActor``
        ``gcs/actor/gcs_actor_manager.cc:310/:429`` + GcsActorScheduler)."""
        if faultpoints.ACTIVE:
            # Fires before registration: an injected failure leaves no
            # half-created actor behind for the retry to collide with.
            await faultpoints.async_fire("gcs.actor.create")
        return await self._create_one_actor(h, frames, conn)

    async def rpc_create_actor_batch(self, h, frames, conn):
        """Batched actor creation: one head RPC covers a whole submission
        burst (reference: the async registration queue in GcsActorManager —
        N registrations amortize one RPC envelope each here). Items
        schedule concurrently; each reports {"ok", "addr", "node_id"} or
        {"err"} so one unschedulable actor never fails its batchmates.
        The caller's correlation id covers the WHOLE batch: a retry after
        a dropped reply replays every item's original outcome via the
        dispatch-level dedup cache — no double-created actors."""
        if faultpoints.ACTIVE:
            # Before ANY item registers: an injected batch failure is
            # retryable-unavailable with nothing half-applied.
            await faultpoints.async_fire("gcs.create_actor_batch")
        per_item = protocol.unpack_multi_frames(
            h.get("fcounts", []), frames
        )

        async def one(item, item_frames):
            try:
                if faultpoints.ACTIVE:
                    await faultpoints.async_fire("gcs.actor.create")
                extras, _ = await self._create_one_actor(
                    item, item_frames, conn
                )
                return {"ok": True, **extras}
            except asyncio.CancelledError:
                raise
            except protocol.RpcError as e:
                return {"err": str(e)}
            except Exception as e:
                return {"err": f"{type(e).__name__}: {e}"}

        results = await asyncio.gather(
            *(one(i, f) for i, f in zip(h.get("items", ()), per_item))
        )
        return {"results": list(results)}, []

    async def _create_one_actor(self, h, frames, conn):
        actor_id = h["actor_id"]
        name = h.get("name") or None
        ns = h.get("namespace", "default")
        if name:
            key = (ns, name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing.state != "DEAD":
                    if h.get("get_if_exists"):
                        return {"existing": existing.to_public()}, []
                    raise protocol.RpcError(
                        f"actor name '{name}' already taken in namespace '{ns}'"
                    )
        info = ActorInfo(
            actor_id=actor_id,
            name=name,
            namespace=ns,
            state="PENDING",
            node_id=None,
            addr=None,
            resources={k: float(v) for k, v in h.get("resources", {}).items()},
            max_restarts=h.get("max_restarts", 0),
            creation_frames=list(frames),
            class_name=h.get("class_name", ""),
            pg_id=(h.get("strategy") or {}).get("pg_id"),
            bundle_index=(h.get("strategy") or {}).get("bundle_index", -1),
            detached=h.get("lifetime") == "detached",
            method_meta=dict(h.get("method_meta") or {}),
        )
        self.actors[actor_id] = info
        if name:
            self.named_actors[(ns, name)] = actor_id
        if not info.detached:
            # Non-detached actors die with their owner (reference:
            # GcsActorManager destroys an actor when its owner worker/job
            # exits — ``gcs_actor_manager.cc OnWorkerDead/OnJobFinished``).
            # The owner is whoever issued create_actor on this connection.
            self._track_actor_owner(conn, actor_id)
        ok = await self._schedule_actor(info, h.get("strategy") or {})
        if not ok:
            info.state = "DEAD"
            info.death_reason = "unschedulable: insufficient resources"
            raise protocol.RpcError(info.death_reason)
        return {"addr": list(info.addr), "node_id": info.node_id}, []

    async def _schedule_actor(self, info: ActorInfo, strategy: dict) -> bool:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if info.state == "DEAD":
                # Killed while pending (e.g. owner disconnected mid-wait):
                # placing it now would orphan an ALIVE actor whose cleanup
                # already ran and permanently leak its node resources.
                return False
            node = self._pick_node(info.resources, strategy)
            if node is None:
                fut = asyncio.get_running_loop().create_future()
                self._pending_waiters.append(fut)
                # actors are the third demand source next to leases and PGs
                self.pending_demands[id(fut)] = {
                    "resources": dict(info.resources), "count": 1,
                    "since": time.time(),
                }
                try:
                    await asyncio.wait_for(fut, timeout=1.0)
                except asyncio.TimeoutError:
                    pass
                finally:
                    self.pending_demands.pop(id(fut), None)
                continue
            if not strategy.get("pg_id"):
                self._node_acquire(node, info.resources)
            try:
                await node.conn.call(
                    "create_actor",
                    {
                        "actor_id": info.actor_id,
                        # Public metadata the hosting worker re-reports if
                        # the head restarts and it re-registers (live
                        # rejoin; reference: gcs_init_data replay).
                        "meta": {
                            "name": info.name,
                            "namespace": info.namespace,
                            "class_name": info.class_name,
                            "resources": info.resources,
                            "detached": info.detached,
                            "method_meta": info.method_meta,
                        },
                    },
                    info.creation_frames,
                )
            except protocol.RpcError as e:
                # Actor __init__ raised: actor is born dead; surface the error.
                if not strategy.get("pg_id"):
                    self._node_release(node, info.resources)
                info.state = "DEAD"
                info.death_reason = str(e)
                self.publish(f"actor:{info.actor_id}", info.to_public())
                raise
            except protocol.ConnectionLost:
                continue  # node died mid-create; try another
            if info.state == "DEAD":
                # Owner disconnected during the create RPC: its cleanup saw
                # PENDING (nothing to kill yet), so undo the placement here.
                try:
                    await node.conn.call(
                        "kill_actor", {"actor_id": info.actor_id}
                    )
                except (protocol.RpcError, protocol.ConnectionLost) as e:
                    logger.debug(
                        "kill_actor %s during create-undo failed: %s",
                        info.actor_id, e,
                    )
                if not strategy.get("pg_id"):
                    self._node_release(node, info.resources)
                    self._wake_waiters()
                return False
            info.node_id = node.node_id
            info.addr = node.addr
            info.state = "ALIVE"
            self._emit_event("ACTOR", "ACTOR_ALIVE", info.actor_id,
                             class_name=info.class_name,
                             node_id=node.node_id,
                             restarts_used=info.restarts_used)
            self.publish(f"actor:{info.actor_id}", info.to_public())
            return True
        return False

    def _release_actor_placement(self, actor: ActorInfo):
        """Return the actor's reserved resources to its (still-alive) node or
        PG bundle. No-op when the node is dead: its whole availability died
        with it."""
        if actor.node_id is None:
            return
        node = self.nodes.get(actor.node_id)
        if node is None or not node.alive:
            return
        if actor.pg_id:
            reserved = self.pg_reserved.get(actor.pg_id)
            pg = self.pgs.get(actor.pg_id)
            if reserved is None or pg is None:
                # PG removed while the actor was alive: its loaned bundle
                # resources return straight to the node.
                self._node_release(node, actor.resources)
                self._wake_waiters()
                return
            indices = (
                [actor.bundle_index]
                if actor.bundle_index >= 0
                else [
                    i for i, nid in enumerate(pg.bundle_nodes)
                    if nid == actor.node_id
                ]
            )
            if indices:
                _release(reserved[indices[0]], actor.resources)
        else:
            self._node_release(node, actor.resources)
        self._wake_waiters()

    async def _on_actor_dead(self, actor: ActorInfo, reason: str):
        if actor.state == "DEAD":
            return
        restartable = actor.restarts_used < actor.max_restarts or actor.max_restarts == -1
        if restartable:
            self._release_actor_placement(actor)
            actor.restarts_used += 1
            actor.state = "RESTARTING"
            self._emit_event("ACTOR", "ACTOR_RESTARTING", actor.actor_id,
                             message=reason,
                             restarts_used=actor.restarts_used)
            actor.death_reason = reason
            self.publish(f"actor:{actor.actor_id}", actor.to_public())
            strategy = {}
            if actor.pg_id:
                strategy = {"pg_id": actor.pg_id, "bundle_index": actor.bundle_index}
            try:
                ok = await self._schedule_actor(actor, strategy)
            except protocol.RpcError:
                ok = False
            if not ok:
                actor.state = "DEAD"
                self.publish(f"actor:{actor.actor_id}", actor.to_public())
        else:
            actor.state = "DEAD"
            actor.death_reason = reason
            self._emit_event("ACTOR", "ACTOR_DEAD", actor.actor_id,
                             message=reason,
                             class_name=actor.class_name)
            if actor.name:
                self.named_actors.pop((actor.namespace, actor.name), None)
            self._release_actor_placement(actor)
            self.publish(f"actor:{actor.actor_id}", actor.to_public())

    async def rpc_actor_exited(self, h, frames, conn):
        """A node reports that an actor exited (clean exit or crash)."""
        actor = self.actors.get(h["actor_id"])
        if actor is None:
            return {}, []
        if h.get("clean"):
            actor.max_restarts = 0  # intentional exit is never restarted
        await self._on_actor_dead(actor, h.get("reason", "actor exited"))
        return {}, []

    def _conn_key(self, conn) -> int:
        """Stable per-connection key + one close hook that tears down ALL
        connection-scoped state (owned actors, outstanding leases)."""
        key = getattr(conn, "_rt_serial", None)
        if key is not None:
            return key
        key = conn._rt_serial = next(self._conn_serial)
        prev = conn.on_close
        loop = asyncio.get_event_loop()

        def _on_close(c):
            # Set BEFORE the async cleanup runs: an rpc_lease that was
            # still waiting for resources when the client died completes
            # later on this loop — it must see the flag and return its
            # grant instead of recording a zombie ledger entry after the
            # ledger was already drained.
            c._rt_conn_dead = True
            if prev is not None:
                try:
                    prev(c)
                except Exception:
                    logger.exception("chained on_close failed")
            if self._shutting_down or loop.is_closed():
                self._conn_actors.pop(key, None)
                self._conn_leases.pop(key, None)
                return
            try:
                loop.call_soon_threadsafe(
                    lambda: spawn_logged(loop, self._on_conn_closed(key),
                                         "gcs.on_conn_closed")
                )
            except RuntimeError:
                pass

        conn.on_close = _on_close
        return key

    async def _on_conn_closed(self, key: int):
        self._release_conn_leases(key)
        await self._on_actor_owner_closed(key)

    def _track_actor_owner(self, conn, actor_id: str):
        self._conn_actors.setdefault(self._conn_key(conn), set()).add(actor_id)

    def _track_conn_lease(self, conn, node_id: str, resources: dict,
                          strategy: dict):
        key = self._conn_key(conn)
        if getattr(conn, "_rt_conn_dead", False):
            # Granted after (or while) the client's disconnect cleanup
            # drains its ledger: hand the resources straight back without
            # touching the ledger (it may hold other not-yet-drained
            # entries).
            self._release_lease_entry(node_id, resources, strategy)
            self._wake_waiters()
            return
        self._conn_leases.setdefault(key, []).append(
            (node_id, resources, strategy)
        )

    def _untrack_conn_lease(self, conn, node_id: str, resources: dict,
                            strategy: dict):
        ledger = self._conn_leases.get(getattr(conn, "_rt_serial", -1))
        if not ledger:
            return
        pg = (strategy or {}).get("pg_id")
        for i, (nid, res, strat) in enumerate(ledger):
            if nid == node_id and res == resources \
                    and (strat or {}).get("pg_id") == pg:
                del ledger[i]
                return

    def _release_lease_entry(self, node_id: str, need: dict, strategy: dict):
        """Return one lease's resources: PG leases to their bundle
        reservation (or the node if the PG is already gone — mirrors
        rpc_release_lease), plain leases to the node."""
        pg_id = (strategy or {}).get("pg_id")
        if pg_id:
            pg = self.pgs.get(pg_id)
            reserved = self.pg_reserved.get(pg_id)
            if pg is not None and reserved is not None:
                idx = (strategy or {}).get("bundle_index", -1)
                indices = [idx] if idx >= 0 else range(len(pg.bundles))
                for i in indices:
                    if pg.bundle_nodes[i] == node_id:
                        _release(reserved[i], need)
                        break
            elif pg is not None:
                node = self.nodes.get(node_id)
                if node is not None and node.alive:
                    self._node_release(node, need)
            return
        node = self.nodes.get(node_id)
        if node is not None and node.alive:
            self._node_release(node, need)

    def _release_conn_leases(self, key: int):
        """Client connection gone: return every lease it still held."""
        for node_id, need, strategy in self._conn_leases.pop(key, ()):
            self._release_lease_entry(node_id, need, strategy)
        self._wake_waiters()

    async def _on_actor_owner_closed(self, key: int):
        """Owner connection gone: kill its non-detached actors (they may be
        ALIVE on some node, or PENDING). Named entries are dropped so the
        name becomes reusable."""
        for actor_id in self._conn_actors.pop(key, set()):
            actor = self.actors.get(actor_id)
            if actor is None or actor.state == "DEAD":
                continue
            actor.max_restarts = 0
            node = self.nodes.get(actor.node_id) if actor.node_id else None
            if node is not None and node.conn is not None and actor.state == "ALIVE":
                try:
                    await node.conn.call(
                        "kill_actor", {"actor_id": actor.actor_id}
                    )
                except (protocol.RpcError, protocol.ConnectionLost) as e:
                    logger.debug(
                        "kill_actor %s on owner disconnect failed "
                        "(node death will reap it): %s", actor.actor_id, e,
                    )
            await self._on_actor_dead(actor, "owner disconnected")

    async def rpc_kill_actor(self, h, frames, conn):
        actor = self.actors.get(h["actor_id"])
        if actor is None:
            return {"found": False}, []
        if h.get("no_restart", True):
            actor.max_restarts = 0
        node = self.nodes.get(actor.node_id) if actor.node_id else None
        if node is not None and node.conn is not None and actor.state == "ALIVE":
            try:
                await node.conn.call("kill_actor", {"actor_id": actor.actor_id})
            except (protocol.RpcError, protocol.ConnectionLost) as e:
                logger.debug(
                    "kill_actor RPC to node %s failed (actor %s marked "
                    "dead regardless): %s", actor.node_id, actor.actor_id, e,
                )
        await self._on_actor_dead(actor, "killed via kill_actor")
        return {"found": True}, []

    async def rpc_get_actor(self, h, frames, conn):
        if "name" in h:
            aid = self.named_actors.get((h.get("namespace", "default"), h["name"]))
            if aid is None:
                return {"found": False}, []
            actor = self.actors.get(aid)
        else:
            actor = self.actors.get(h["actor_id"])
        if actor is None:
            return {"found": False}, []
        return {"found": True, "actor": actor.to_public()}, []

    async def rpc_list_actors(self, h, frames, conn):
        return {"actors": [a.to_public() for a in self.actors.values()]}, []

    # ------------------------------------------------------- placement groups

    async def rpc_create_pg(self, h, frames, conn):
        """Two-phase bundle reservation (reference: GcsPlacementGroupScheduler
        prepare/commit ``gcs_placement_group_scheduler.h:115-117``). On a
        single head the phases collapse, but bundles are still all-or-nothing."""
        pg_id = h["pg_id"]
        bundles = [
            {k: float(v) for k, v in b.items()} for b in h["bundles"]
        ]
        strategy = h.get("pg_strategy", "PACK")
        pg = PlacementGroupInfo(
            pg_id=pg_id, bundles=bundles, strategy=strategy, state="PENDING",
            bundle_nodes=[None] * len(bundles), name=h.get("name", ""),
        )
        self.pgs[pg_id] = pg
        deadline = time.monotonic() + h.get("timeout", 30.0)
        while time.monotonic() < deadline:
            if pg.state == "REMOVED":  # removed while we waited
                return {"state": "REMOVED"}, []
            if self._commit_pg(pg):
                return {"state": "CREATED", "bundle_nodes": pg.bundle_nodes}, []
            # Same demand-driven reclaim as rpc_lease: idle cached slots on
            # workers are the usual reason an otherwise-free cluster can't
            # place a bundle.
            self._maybe_reclaim_leases(bundles)
            fut = asyncio.get_running_loop().create_future()
            self._pending_waiters.append(fut)
            try:
                await asyncio.wait_for(fut, timeout=1.0)
            except asyncio.TimeoutError:
                pass
        # The group STAYS registered as PENDING: whenever resources free
        # (_wake_waiters), the head retries it — the reference reschedules
        # pending placement groups the same way
        # (gcs_placement_group_manager SchedulePendingPlacementGroups);
        # clients poll get_pg and observe the late CREATED.
        return {"state": "PENDING"}, []

    def _commit_pg(self, pg) -> bool:
        """All-or-nothing bundle commit; publishes + flips state on
        success. Shared by the creation RPC and the pending-PG retry."""
        if pg.state == "CREATED":
            return True
        placement = self._try_place_bundles(pg)
        if placement is None:
            return False
        for i, node in enumerate(placement):
            self._node_acquire(node, pg.bundles[i])
            pg.bundle_nodes[i] = node.node_id
        self.pg_reserved[pg.pg_id] = [dict(b) for b in pg.bundles]
        pg.state = "CREATED"
        self._emit_event("PLACEMENT_GROUP", "PG_CREATED", pg.pg_id,
                         strategy=pg.strategy, bundles=len(pg.bundles))
        self.publish(f"pg:{pg.pg_id}", pg.to_public())
        return True

    def _schedule_pending_pgs(self):
        for pg in list(self.pgs.values()):
            if pg.state == "PENDING":
                self._commit_pg(pg)

    def _try_place_bundles(self, pg) -> Optional[List[NodeInfo]]:
        # Work on a scratch copy of availability so it's all-or-nothing.
        # Standby (warm pool) nodes are excluded: bundles reserve capacity
        # long-term, which would silently consume the instant-activation
        # reserve (lease/actor demand activates standbys via _pick_node).
        scratch = {
            n.node_id: dict(n.available)
            for n in self.nodes.values() if n.alive and not n.standby
        }
        chosen: List[str] = []
        nodes_sorted = sorted(
            (n for n in self.nodes.values() if n.alive and not n.standby),
            key=lambda n: n.node_id,
        )
        for i, bundle in enumerate(pg.bundles):
            placed = None
            if pg.strategy in ("STRICT_PACK",):
                cands = [chosen[0]] if chosen else [n.node_id for n in nodes_sorted]
            elif pg.strategy == "STRICT_SPREAD":
                cands = [n.node_id for n in nodes_sorted if n.node_id not in chosen]
            elif pg.strategy == "SPREAD":
                cands = sorted(
                    (n.node_id for n in nodes_sorted),
                    key=lambda nid: chosen.count(nid),
                )
            else:  # PACK: prefer reusing nodes already chosen
                cands = sorted(
                    (n.node_id for n in nodes_sorted),
                    key=lambda nid: (0 if nid in chosen else 1, nid),
                )
            for nid in cands:
                if nid in scratch and _fits(scratch[nid], bundle):
                    _acquire(scratch[nid], bundle)
                    placed = nid
                    break
            if placed is None:
                return None
            chosen.append(placed)
        return [self.nodes[nid] for nid in chosen]

    async def rpc_remove_pg(self, h, frames, conn):
        pg = self.pgs.get(h["pg_id"])
        if pg is None or pg.state == "REMOVED":
            return {}, []
        self._emit_event("PLACEMENT_GROUP", "PG_REMOVED", pg.pg_id)
        if pg.state == "CREATED":
            for i, nid in enumerate(pg.bundle_nodes):
                node = self.nodes.get(nid) if nid else None
                if node is not None and node.alive:
                    # Return whatever of the bundle is not currently loaned out;
                    # loaned resources return via release_lease.
                    remainder = self.pg_reserved.get(pg.pg_id)
                    self._node_release(
                        node,
                        remainder[i] if remainder is not None else pg.bundles[i],
                    )
        pg.state = "REMOVED"
        self.pg_reserved.pop(pg.pg_id, None)
        self._wake_waiters()
        self.publish(f"pg:{pg.pg_id}", pg.to_public())
        return {}, []

    async def rpc_get_pg(self, h, frames, conn):
        pg = self.pgs.get(h["pg_id"])
        if pg is None:
            return {"found": False}, []
        return {"found": True, "pg": pg.to_public()}, []

    async def rpc_list_pgs(self, h, frames, conn):
        return {"pgs": [p.to_public() for p in self.pgs.values()]}, []

    # ----------------------------------------------------------------- pubsub

    async def rpc_subscribe(self, h, frames, conn):
        self.subscribers[h["channel"]].append(conn)
        return {}, []

    async def rpc_publish(self, h, frames, conn):
        self.publish(h["channel"], h.get("data"), frames)
        return {}, []

    # ---------------------------------------------------------- log plane

    async def rpc_worker_logs(self, h, frames, conn):
        """A worker's log monitor pushed new lines: buffer a bounded ring
        per node for rt logs/dashboard, fan out live to subscribed
        drivers (reference behavior: log_monitor publish + driver echo)."""
        buf = self.log_buffer.get(h["node_id"])
        if buf is None:
            buf = self.log_buffer[h["node_id"]] = deque(
                maxlen=self._LOG_BUFFER_LINES
            )
        pid, stream = h.get("pid"), h.get("stream", "stdout")
        for line in h.get("lines", ()):
            buf.append((stream, pid, line))
        # "shared": the worker's spawn job is not any registered driver job
        # (rt start / autoscaler workers get a random JobID) — such lines
        # belong to no one driver, so every driver may echo them. Without
        # this, shared-cluster topologies would never see remote prints.
        job = h.get("job_id", "")
        self.publish("worker_logs", {
            "node_id": h["node_id"], "pid": pid, "stream": stream,
            "job_id": job, "shared": job not in self.jobs,
            "lines": h.get("lines", []),
        })
        return {}, []

    async def rpc_get_logs(self, h, frames, conn):
        """Read back buffered worker logs: optional node filter + tail
        count (rt logs / dashboard logs view)."""
        node = h.get("node_id")
        try:
            tail = int(h["tail"]) if h.get("tail") is not None else 1000
            tail = max(tail, 0)
        except (TypeError, ValueError):
            tail = 1000
        out = []
        items = (
            [(node, self.log_buffer.get(node))] if node
            else list(self.log_buffer.items())
        )
        items = [(nid, buf) for nid, buf in items if buf]
        # The budget is split ACROSS nodes (lines carry no global order, so
        # a concat-then-truncate would silently drop whole earlier nodes).
        # Fair allocation, quiet nodes' unused share flowing to busy ones:
        # walk ascending by buffer size, each node taking at most an even
        # split of what remains.
        remaining = tail
        left = len(items)
        for nid, buf in sorted(items, key=lambda x: len(x[1])):
            take = min(len(buf), remaining // left) if left else 0
            left -= 1
            remaining -= take
            if take <= 0:
                continue
            # islice, not list(buf)[-n:]: the dashboard polls this every
            # 2s and a full 10k-entry copy per node per poll is pure churn.
            for stream, pid, line in itertools.islice(
                buf, len(buf) - take, None
            ):
                out.append({"node_id": nid, "pid": pid, "stream": stream,
                            "line": line})
        return {"lines": out}, []

    def publish(self, channel: str, data, frames: List[bytes] = ()):
        if faultpoints.ACTIVE:
            try:
                # error and drop both lose the publish for every
                # subscriber (pubsub is fire-and-forget by contract).
                if faultpoints.fire("gcs.pubsub.publish") == "drop":
                    return
            except ConnectionError as e:
                logger.debug("injected publish loss on %s: %s", channel, e)
                return
        for conn in list(self.subscribers.get(channel, [])):
            try:
                conn.notify("pubsub", {"channel": channel, "data": data}, frames)
            except protocol.ConnectionLost:
                self.subscribers[channel].remove(conn)

    # --------------------------------------------------------- object dir

    async def rpc_object_register(self, h, frames, conn):
        # Owners flush registrations in batches ("items") — one notify per
        # put-burst, not per object; single oid/meta kept for compat.
        # Each entry is stamped with the head's wall clock ("_t"): the
        # leak detector's grace window measures age on ONE clock instead
        # of trusting N workers' clocks (a re-registration — e.g. a spill
        # transition — refreshes the stamp, which is correct: the entry
        # was just proven live).
        now = time.time()
        if "items" in h:
            items = h["items"]
            # Both batch shapes are live: dict from rpc-level callers,
            # pair list from the worker's ordered ref-op drain.
            pairs = items.items() if isinstance(items, dict) else items
            for oid, meta in pairs:
                if isinstance(meta, dict):
                    meta["_t"] = now
                self.object_dir[oid] = meta
        else:
            meta = h["meta"]
            if isinstance(meta, dict):
                meta["_t"] = now
            self.object_dir[h["oid"]] = meta
        return {}, []

    async def rpc_object_lookup(self, h, frames, conn):
        meta = self.object_dir.get(h["oid"])
        return {"found": meta is not None, "meta": meta}, []

    async def rpc_object_lookup_batch(self, h, frames, conn):
        """Multi-oid directory lookup: one round-trip resolves a whole
        get()/wait() batch (reference: the owner-resolved directory serves
        location batches, ``ownership_object_directory.h``). ``metas[i]``
        is None for oids without a directory entry (inline objects live
        only in their owner's memory store and are pulled from the owner)."""
        d = self.object_dir
        return {"metas": [d.get(oid) for oid in h["oids"]]}, []

    async def rpc_object_free(self, h, frames, conn):
        metas = [self.object_dir.pop(oid, None) for oid in h["oids"]]
        # Fan out so borrower processes evict cached copies/pins.
        self.publish("object_free", {"oids": h["oids"]})
        return {"metas": [m for m in metas if m]}, []

    # ------------------------------------------------------------- jobs/state

    async def rpc_export_events(self, h, frames, conn):
        """Recent structured export events (reference: the aggregator's
        event query surface); filterable by source/event type."""
        if getattr(self, "events", None) is None:
            return {"events": []}, []
        return {"events": self.events.recent(
            limit=h.get("limit", 100),
            source_type=h.get("source_type"),
            event_type=h.get("event_type"),
        )}, []

    async def rpc_register_job(self, h, frames, conn):
        self.jobs[h["job_id"]] = {
            "job_id": h["job_id"], "start_time": time.time(), "state": "RUNNING",
        }
        self._wal_append({"op": "job", "job": self.jobs[h["job_id"]]})
        self._emit_event("JOB", "JOB_STARTED", h["job_id"])
        return {}, []

    async def rpc_list_jobs(self, h, frames, conn):
        return {"jobs": list(self.jobs.values())}, []

    async def rpc_list_objects(self, h, frames, conn):
        """Directory listing with server-side filters and honest
        truncation: filters ([(key, op, value)], op in =/!=) run over the
        flattened row BEFORE the limit slice, and the reply reports
        {recorded, dropped} like ``list_task_events`` does — a truncated
        listing is visible, never a silent slice."""
        limit = h.get("limit", 1000)
        filters = h.get("filters") or ()
        rows = []
        for oid, meta in list(self.object_dir.items()):
            meta = meta if isinstance(meta, dict) else {}
            row = {
                "object_id": oid,
                "bytes": int(meta.get("size") or 0),
                "node": meta.get("node"),
                "owner": meta.get("owner"),
                "spilled": bool(meta.get("spill")),
                "task": oid[:48],
                "meta": meta,
            }
            keep = True
            for key, op, value in filters:
                have = str(row.get(key))
                if op == "=":
                    keep = have == str(value)
                elif op == "!=":
                    keep = have != str(value)
                else:
                    raise protocol.RpcError(
                        f"unsupported filter op {op!r} (want = or !=)"
                    )
                if not keep:
                    break
            if keep:
                rows.append(row)
        recorded = len(rows)
        if limit:
            rows = rows[:limit]
        return {"objects": rows, "recorded": recorded,
                "dropped": max(recorded - len(rows), 0)}, []

    async def rpc_cluster_load(self, h, frames, conn):
        """Autoscaler feed: unsatisfied demands + pending PG bundles + the
        per-node resource view (reference: gcs_autoscaler_state_manager.cc)."""
        pending_pgs = [
            {"pg_id": pg.pg_id, "bundles": pg.bundles, "strategy": pg.strategy}
            for pg in self.pgs.values() if pg.state == "PENDING"
        ]
        return {
            "pending": list(self.pending_demands.values()),
            "pending_pgs": pending_pgs,
            "nodes": self._public_nodes(),
        }, []

    async def rpc_metrics_push(self, h, frames, conn):
        """Latest metric snapshot per worker (reference: per-node metrics
        agent collecting for the Prometheus scrape). node_id rides along so
        node death can drop the worker's series (stale gauges poison
        Prometheus aggregates)."""
        self.worker_metrics[h["worker_id"]] = {
            "node_id": h.get("node_id"), "metrics": h["metrics"],
        }
        return {}, []

    async def rpc_metrics_snapshot(self, h, frames, conn):
        return {
            "snapshots": {
                wid: rec["metrics"] for wid, rec in self.worker_metrics.items()
            },
            # worker -> node map: the /metrics rollup aggregates series
            # per NODE (one scrape endpoint covering the whole cluster).
            "nodes": {
                wid: rec.get("node_id")
                for wid, rec in self.worker_metrics.items()
            },
        }, []

    async def rpc_memory_summary(self, h, frames, conn):
        """Object-plane cluster snapshot: fan ``memstat_drain`` out to
        every connected process (the ``flight_snapshot`` pattern — remote
        drivers own objects too; tool clients answer without a payload
        and are skipped), and return the raw parts the memtrack join
        needs: per-process accounting snapshots, the head's directory
        (bounded, with honest truncation counts), the task-id → name map
        for creating-task attribution, and the alive-node set."""
        targets = {}
        for n in self.nodes.values():
            if n.alive and n.conn is not None:
                targets[id(n.conn)] = (n.conn, n.node_id)
        for c in (self.server.connections if self.server else ()):
            targets.setdefault(id(c), (c, None))

        async def one(c, label):
            try:
                hh, _ = await asyncio.wait_for(
                    c.call("memstat_drain", {}), timeout=10,
                )
            except (asyncio.TimeoutError, protocol.RpcError,
                    protocol.ConnectionLost, OSError) as e:
                logger.debug("memstat_drain from %s failed: %s",
                             label or c.name, e)
                return None
            s = hh.get("memstat")
            if s and label:
                s.setdefault("node", label)
            return s

        results = await asyncio.gather(
            *(one(c, label) for c, label in targets.values())
        )
        # One snapshot per PROCESS (a peer reachable over two connections
        # answers twice): keyed by worker id, keep the first.
        by_worker = {}
        for s in results:
            if s:
                by_worker.setdefault(s.get("worker") or id(s), s)
        limit = h.get("limit", 10000)
        directory = [
            {"oid": oid, "meta": meta}
            for oid, meta in itertools.islice(
                self.object_dir.items(), limit or None
            )
        ]
        names = {}
        for e in self.task_events:
            tid = e.get("task_id")
            if tid:
                names[tid] = e.get("name")
        recorded = len(self.object_dir)
        return {
            "snapshots": list(by_worker.values()),
            "directory": directory,
            "recorded": recorded,
            "dropped": max(recorded - len(directory), 0),
            "tasks": names,
            "nodes": [n.node_id for n in self.nodes.values() if n.alive],
            "now": time.time(),
            "enabled": bool(by_worker),
        }, []

    async def rpc_task_event(self, h, frames, conn):
        return await self.rpc_task_events(
            {"events": [h["event"]]}, frames, conn
        )

    def builtin_metrics(self) -> Dict[str, float]:
        """Head-derived cluster series for /metrics (reference: the GCS-side
        series the reference dashboard's Grafana panels graph)."""
        counters = self._task_state_counts
        return {
            "rt_nodes_alive": float(
                sum(1 for n in self.nodes.values() if n.alive)
            ),
            "rt_nodes_dead": float(len(self.dead_nodes)),
            "rt_actors_alive": float(
                sum(1 for a in self.actors.values() if a.state == "ALIVE")
            ),
            "rt_placement_groups": float(len(self.pgs)),
            "rt_pending_demands": float(len(self.pending_demands)),
            "rt_object_dir_entries": float(len(self.object_dir)),
            "rt_tasks_finished_total": float(counters.get("FINISHED", 0)),
            "rt_tasks_failed_total": float(counters.get("FAILED", 0)),
        }

    async def rpc_task_events(self, h, frames, conn):
        """Task-event sink (reference: GcsTaskManager fed by the per-worker
        ``task_event_buffer.h`` in 4Hz batches); bounded ring for the state
        API. Oversized string fields are clamped so one hostile event
        cannot dominate the ring's memory."""
        events = h.get("events", [])
        ring = self.task_events
        for e in events:
            s = e.get("state")
            if s:
                self._task_state_counts[s] = (
                    self._task_state_counts.get(s, 0) + 1
                )
            name = e.get("name")
            if isinstance(name, str) and len(name) > 256:
                e["name"] = name[:256]
            ring.append(e)
        self._task_events_total += len(events)
        return {}, []

    async def rpc_list_task_events(self, h, frames, conn):
        limit = h.get("limit", 1000)
        events = list(self.task_events)
        return {
            "events": events[-limit:] if limit else events,
            "recorded": self._task_events_total,
            "dropped": max(self._task_events_total - len(events), 0),
        }, []

    # ------------------------------------------------------ job submission
    # Reference analog: dashboard/modules/job/job_manager.py:58 — submitted
    # entrypoints run as supervised subprocesses with captured logs and a
    # PENDING→RUNNING→SUCCEEDED/FAILED/STOPPED lifecycle. The head owns them
    # here (round-1 single head process).

    def _job_log_path(self, sub_id: str) -> str:
        import os
        import tempfile

        d = os.path.join(tempfile.gettempdir(), "ray_tpu", "jobs")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{sub_id}.log")

    async def rpc_submit_job(self, h, frames, conn):
        import os
        import subprocess
        import uuid

        sub_id = h.get("submission_id") or f"raysubmit_{uuid.uuid4().hex[:16]}"
        if sub_id in self.job_procs:
            raise protocol.RpcError(f"job {sub_id} already exists")
        env = dict(os.environ)
        runtime_env = h.get("runtime_env") or {}
        env.update(runtime_env.get("env_vars") or {})
        env["RAY_TPU_ADDRESS"] = f"{self.addr[0]}:{self.addr[1]}"
        # The entrypoint must be able to import the framework regardless of
        # its cwd (python puts the script dir, not cwd, on sys.path).
        import ray_tpu

        pkg_parent = os.path.dirname(os.path.dirname(ray_tpu.__file__))
        existing = env.get("PYTHONPATH")
        env["PYTHONPATH"] = (
            pkg_parent + os.pathsep + existing if existing else pkg_parent
        )
        log_path = self._job_log_path(sub_id)
        logf = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                h["entrypoint"], shell=True, stdout=logf,
                stderr=subprocess.STDOUT, env=env,
                cwd=runtime_env.get("working_dir") or None,
            )
        except OSError as e:
            logf.close()
            raise protocol.RpcError(f"spawn failed: {e}")
        logf.close()
        self.job_procs[sub_id] = proc
        self.jobs[sub_id] = {
            "job_id": sub_id, "submission_id": sub_id, "type": "SUBMISSION",
            "entrypoint": h["entrypoint"], "status": "RUNNING",
            "start_time": time.time(), "end_time": None, "log_path": log_path,
            "metadata": h.get("metadata") or {},
        }
        self._wal_append({"op": "job", "job": dict(self.jobs[sub_id])})
        spawn_logged(None, self._watch_job(sub_id, proc), "gcs.watch_job")
        return {"submission_id": sub_id}, []

    async def _watch_job(self, sub_id: str, proc):
        while proc.poll() is None:
            await asyncio.sleep(0.1)
        info = self.jobs.get(sub_id)
        if info is not None and info["status"] in ("RUNNING", "STOPPING"):
            if info.get("stop_requested"):
                info["status"] = "STOPPED"
            else:
                info["status"] = (
                    "SUCCEEDED" if proc.returncode == 0 else "FAILED"
                )
            info["end_time"] = time.time()
            self._wal_append({"op": "job", "job": dict(info)})

    async def rpc_job_status(self, h, frames, conn):
        info = self.jobs.get(h["submission_id"])
        if info is None:
            return {"found": False}, []
        return {"found": True, "job": info}, []

    async def rpc_job_logs(self, h, frames, conn):
        info = self.jobs.get(h["submission_id"])
        if info is None or "log_path" not in info:
            return {"found": False}, []
        try:
            with open(info["log_path"], "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        return {"found": True}, [data]

    async def rpc_stop_job(self, h, frames, conn):
        proc = self.job_procs.get(h["submission_id"])
        info = self.jobs.get(h["submission_id"])
        if proc is None or info is None:
            return {"stopped": False}, []
        if proc.poll() is None:
            # SIGTERM with SIGKILL escalation; STOPPED is reported only once
            # the process actually exits (_watch_job), so a trap-and-ignore
            # entrypoint can't look terminal while holding resources.
            info["stop_requested"] = True
            info["status"] = "STOPPING"
            proc.terminate()
            spawn_logged(None, self._escalate_stop(proc),
                         "gcs.escalate_stop")
        return {"stopped": True}, []

    async def _escalate_stop(self, proc, grace_s: float = 3.0):
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return
            await asyncio.sleep(0.1)
        try:
            proc.kill()
        except ProcessLookupError:
            pass

    async def rpc_ping(self, h, frames, conn):
        return {"t": time.time()}, []
