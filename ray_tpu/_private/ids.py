"""Binary IDs for jobs, tasks, actors, objects, nodes, placement groups.

TPU-native analog of the reference ID system (reference:
``src/ray/common/id.h``, spec in ``src/ray/design_docs/id_specification.md``).
We keep the same *shape* of the scheme — fixed-width binary IDs, object IDs
derived from (owner task, return index), actor IDs embedding the job — but the
layout is our own: every ID is raw bytes with a short type tag, rendered as
hex. IDs are hashable, comparable, and msgpack/pickle-friendly.
"""
from __future__ import annotations

import itertools
import os
import threading

_UNIQUE_LEN = 16  # bytes of entropy for top-level IDs


class BaseID:
    """Fixed-width binary identifier."""

    __slots__ = ("_bytes",)
    _len = _UNIQUE_LEN

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != self._len:
            raise ValueError(
                f"{type(self).__name__} requires {self._len} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls._len))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls._len)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self._len

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    _len = 4


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    """ActorID = job id (4 bytes) + 12 random bytes."""

    _len = 16

    @classmethod
    def of(cls, job_id: JobID):
        return cls(job_id.binary() + os.urandom(12))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:4])


class TaskID(BaseID):
    """TaskID = actor id (16 bytes, nil for normal tasks) + 8 unique bytes.

    The unique suffix is a per-process 4-byte random prefix (pid-mixed) +
    4-byte counter with a RANDOM start: collision-free within a process and
    ~10x cheaper than an os.urandom syscall per task on the submit path.
    Cross-process collision needs BOTH an equal prefix (2^-32) and
    overlapping counter windows (~tasks/2^32 given the random start), i.e.
    ~2^-44 per process pair for million-task processes — comparable to the
    8-random-byte scheme this replaced.
    """

    _len = 24
    _NIL_PREFIX = b"\x00" * 16
    # itertools.count is a single C call per next(): atomic under the GIL,
    # unlike a load-add-store on a class attribute (two driver threads
    # racing that would mint duplicate TaskIDs).
    _seq = itertools.count(int.from_bytes(os.urandom(4), "big"))
    _rand = (
        int.from_bytes(os.urandom(4), "big") ^ (os.getpid() & 0xFFFFFFFF)
    ).to_bytes(4, "big")

    @classmethod
    def of(cls, actor_id: ActorID | None = None):
        prefix = actor_id.binary() if actor_id is not None else cls._NIL_PREFIX
        seq = next(cls._seq) & 0xFFFFFFFF
        return cls(prefix + cls._rand + seq.to_bytes(4, "big"))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:16])


# A forked child must not continue the parent's TaskID sequence.
os.register_at_fork(
    after_in_child=lambda: (
        setattr(TaskID, "_rand", (int.from_bytes(os.urandom(4), "big") ^ (os.getpid() & 0xFFFFFFFF)).to_bytes(4, "big")),
        setattr(TaskID, "_seq", itertools.count(int.from_bytes(os.urandom(4), "big"))),
    )
)


class ObjectID(BaseID):
    """ObjectID = task id (24 bytes) + return index (4 bytes big-endian).

    Deterministically derived from the producing task, so lineage
    reconstruction can recompute the same IDs (reference semantics:
    ``src/ray/common/id.h`` ObjectID::FromIndex).
    """

    _len = 28

    @classmethod
    def for_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # Put objects use the high bit of the index space.
        return cls(task_id.binary() + (0x80000000 | put_index).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:24])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[24:], "big") & 0x7FFFFFFF


class PlacementGroupID(BaseID):
    _len = 16


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
