"""Binary IDs for jobs, tasks, actors, objects, nodes, placement groups.

TPU-native analog of the reference ID system (reference:
``src/ray/common/id.h``, spec in ``src/ray/design_docs/id_specification.md``).
We keep the same *shape* of the scheme — fixed-width binary IDs, object IDs
derived from (owner task, return index), actor IDs embedding the job — but the
layout is our own: every ID is raw bytes with a short type tag, rendered as
hex. IDs are hashable, comparable, and msgpack/pickle-friendly.
"""
from __future__ import annotations

import os
import threading

_UNIQUE_LEN = 16  # bytes of entropy for top-level IDs


class BaseID:
    """Fixed-width binary identifier."""

    __slots__ = ("_bytes",)
    _len = _UNIQUE_LEN

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != self._len:
            raise ValueError(
                f"{type(self).__name__} requires {self._len} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls._len))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls._len)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self._len

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    _len = 4


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    """ActorID = job id (4 bytes) + 12 random bytes."""

    _len = 16

    @classmethod
    def of(cls, job_id: JobID):
        return cls(job_id.binary() + os.urandom(12))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:4])


class TaskID(BaseID):
    """TaskID = actor id (16 bytes, nil for normal tasks) + 8 random bytes."""

    _len = 24

    @classmethod
    def of(cls, actor_id: ActorID | None = None):
        prefix = actor_id.binary() if actor_id is not None else b"\x00" * 16
        return cls(prefix + os.urandom(8))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:16])


class ObjectID(BaseID):
    """ObjectID = task id (24 bytes) + return index (4 bytes big-endian).

    Deterministically derived from the producing task, so lineage
    reconstruction can recompute the same IDs (reference semantics:
    ``src/ray/common/id.h`` ObjectID::FromIndex).
    """

    _len = 28

    @classmethod
    def for_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # Put objects use the high bit of the index space.
        return cls(task_id.binary() + (0x80000000 | put_index).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:24])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[24:], "big") & 0x7FFFFFFF


class PlacementGroupID(BaseID):
    _len = 16


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
