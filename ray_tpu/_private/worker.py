"""CoreWorker: the per-process runtime for drivers and workers.

TPU-native analog of the reference ``src/ray/core_worker/`` (``CoreWorker``
``core_worker.h:167``) plus the Python half (``python/ray/_private/worker.py``).
One instance lives in every process. It owns:

- the process's RPC service (tasks are *pushed directly* worker→worker, as in
  the reference's ``PushNormalTask``/``PushActorTask`` — the scheduler is out
  of the data path once a lease is granted),
- the in-process memory store for small objects (CoreWorkerMemoryStore),
- the shm store client for large objects (plasma analog),
- ownership + borrow refcounting (``reference_counter.h`` semantics, reduced:
  owner tracks local refs + outstanding task-arg borrows),
- lease caching per scheduling key (``normal_task_submitter.h:271``),
- actor submission with per-handle sequence numbers and restart-aware
  reconnect (``actor_task_submitter.cc:168/:582``),
- task execution with per-actor ordered queues and concurrency groups.

Threading model: a single asyncio "core loop" runs all networking (driver: a
daemon thread; worker: the main thread). User/task code runs in executor
threads and talks to the loop via run_coroutine_threadsafe — the analog of the
reference's io_service + task execution threads.
"""
from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import threading
import time
import traceback
from concurrent.futures import (
    CancelledError as SyncCancelledError,
    Future as SyncFuture,
    ThreadPoolExecutor,
    TimeoutError as SyncTimeoutError,
)
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu._private import (
    devstore,
    faultpoints,
    flight,
    memtrack,
    protocol,
    serialization,
    specframe,
    taskpath,
)
from ray_tpu._private.asyncio_util import spawn_logged, spawn_threadsafe
from ray_tpu._private.backoff import Backoff
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    WorkerID,
)
from ray_tpu.native.arena import HybridShmStore
from ray_tpu._private.ringconn import MessageTooBig
from ray_tpu._private.serialization import SerializationContext
from ray_tpu.object_ref import ObjectRef, collect_refs_during

logger = logging.getLogger(__name__)

# In-flight marker for the actor-push corr-dedup cache (_apush_begin).
_APUSH_WIP = object()

# Reply-window dwell below this records no ``reply-window`` phase span:
# the ring hot path's normal dwell is one sink micro-batch (~1ms) and a
# per-result span there is pure instrumentation tax; the unrecorded
# sliver stays inside derived reply-ack (never disappears from the sum).
_WINDOW_DWELL_MIN_S = 0.002


def _lineage_bytes_limit() -> int:
    from ray_tpu._private.config import rt_config

    return rt_config.lineage_bytes

INLINE_OBJECT_MAX = 100 * 1024  # small objects travel inline / live in memory store
FN_NS = "fn"

# Actor identity for async actor methods (sync methods use the thread-local
# CoreWorker.current_actor_id; coroutines need a contextvar instead).
import contextvars

_async_actor_id: contextvars.ContextVar = contextvars.ContextVar(
    "rt_async_actor_id", default=None
)
_async_task_id: contextvars.ContextVar = contextvars.ContextVar(
    "rt_async_task_id", default=None
)


def current_task_id_hex() -> Optional[str]:
    """Task ID of the currently-executing task/actor method, or None."""
    tid = _async_task_id.get()
    if tid is not None:
        return tid
    w = global_worker
    if w is None:
        return None
    tid = getattr(w.current_task_id, "value", None)
    return tid.hex() if tid is not None else None


def current_actor_id_hex() -> Optional[str]:
    """Actor ID of the currently-executing actor method/constructor, or None
    (reference: ``runtime_context.get_actor_id``)."""
    aid = _async_actor_id.get()
    if aid is not None:
        return aid
    w = global_worker
    if w is None:
        return None
    return getattr(w.current_actor_id, "value", None)


def _loads_maybe(frames):
    ctx = SerializationContext()
    return ctx.deserialize_frames(frames)


def _intern_worthy(a) -> bool:
    """Cheap pre-serialization shape test for per-arg framing: splitting
    an argument into its own frames costs one extra serialize per call,
    so only shapes that can plausibly repeat at or above
    ``arg_intern_min_bytes`` (the "same config dict to 10k tasks" shape)
    earn a section. Varying scalars and tiny strings stay inline in the
    skeleton — they would never intern anyway."""
    if isinstance(a, (dict, list, tuple, set, frozenset)):
        return bool(a)
    if isinstance(a, (str, bytes, bytearray)):
        return len(a) >= 64
    return not isinstance(a, (bool, int, float, complex, type(None)))


@dataclass(eq=False)  # identity eq: `slot in slots` must not field-compare
class _LeaseSlot:
    node_id: str
    addr: Tuple[str, int]
    busy: int = 0
    draining: bool = False  # evicted (e.g. OOM); release once in-flight done
    # When this slot last went idle: per-slot release (an idle slot pins a
    # whole CPU at the head — holding it while a sibling slot runs a long
    # task starves every other lease requester, e.g. nested tasks).
    idle_since: float = field(default_factory=time.monotonic)
    # Adaptive in-flight push window (specframe.PushWindow), created on
    # first push when rt_config.push_window is on; None = fixed fan-out.
    pwin: Any = None
    # Loop-side rendezvous for pushers parked on a full window: every
    # settle/release sets it, parked siblings re-check their grant.
    win_event: Any = None
    # Round 20: the pusher-shard loop this slot's pushers first ran on
    # (peer-address affinity invariant — the window/event above are only
    # single-loop-safe because a slot never migrates between shards).
    shard_loop: Any = None


class _LeaseSet:
    """Cached leases + pending queue for one scheduling key."""

    def __init__(self, resources: Dict[str, float], strategy: dict):
        self.resources = resources
        self.strategy = strategy
        self.slots: List[_LeaseSlot] = []
        # deque: pushers pop from the FRONT; a list's pop(0) memmoves the
        # whole backlog per task (O(n^2) across a queued-1M submission).
        self.pending: deque = deque()
        # Round 20: guards the peek+pop sections of the pack loop ONLY
        # when pushers run on sharded loops (two shards draining one
        # scheduling key would otherwise race the head item). The
        # single-loop path never takes it.
        self.plock = threading.Lock()
        self.requesting = False
        self.rr = 0  # rotating slot-pick cursor (see _pump_leases)
        # True after a full rotation found no pusher headroom; cleared when
        # any pusher finishes or the slot set changes. Skips the O(slots)
        # scan per queued item while the backlog is deep.
        self.saturated = False
        # node_id -> monotonic deadline: avoid leasing there (OOM backoff)
        self.avoid: Dict[str, float] = {}
        self.last_active = time.monotonic()
        self.reaper_running = False
        # Taskpath plane: when the last lease grant landed, and whether it
        # activated a warm-pool standby — names a queued task's wait
        # (submit-queue vs lease-wait vs warm-pool-hit) at pop time.
        self.last_grant_t = 0.0
        self.last_grant_warm = False


class _PendingActorCreate:
    """One deferred (batched) actor creation: wire payload until the
    batch flushes, rendezvous after. ``event`` serves caller threads
    (handle serialization, kill); ``fut`` serves coroutines and is
    created by the loop-side drain."""

    __slots__ = ("aid", "header", "frames", "borrows", "event", "fut",
                 "error")

    def __init__(self, aid: str, header: dict, frames: List[bytes],
                 borrows: list):
        self.aid = aid
        self.header = header
        self.frames = frames
        self.borrows = borrows
        self.event = threading.Event()
        self.fut: Optional[asyncio.Future] = None
        self.error: Optional[str] = None


class _ActorChannel:
    """Caller-side channel to one actor: ordered seq numbers + reconnect."""

    def __init__(self, actor_id: str, addr: Optional[Tuple[str, int]]):
        self.actor_id = actor_id
        self.addr = tuple(addr) if addr else None
        self.seq = 0
        self.epoch = 0  # bumps on every (re)connect: a fresh ordering domain
        self.conn: Optional[protocol.Connection] = None
        self.lock = asyncio.Lock()
        self.dead = False
        self.death_reason = ""


class _ActorInstance:
    """Executor-side state for one hosted actor.

    Concurrency groups (reference:
    ``core_worker/task_execution/concurrency_group_manager.h:38``): each
    named group gets its OWN executor pool and async semaphore, so a slow
    call in one group (a long "compute" step) cannot block calls routed to
    another (a "health" ping). The unnamed default group uses
    max_concurrency. Per-caller ordered admission stays global — order is
    decided at queue time, isolation at execution time."""

    def __init__(self, actor_id: str, instance, max_concurrency: int,
                 is_async: bool,
                 concurrency_groups: Optional[Dict[str, int]] = None):
        self.actor_id = actor_id
        self.instance = instance
        self.is_async = is_async
        self.max_concurrency = max_concurrency
        self.pool = ThreadPoolExecutor(
            max_workers=max_concurrency, thread_name_prefix=f"actor-{actor_id[:8]}"
        )
        self.groups: Dict[str, ThreadPoolExecutor] = {}
        # Coroutine methods execute on the dedicated async-actor loop, and
        # an asyncio.Semaphore binds to the loop that first acquires it —
        # concurrency gating for coroutines happens THERE, never on the
        # core loop (sync methods are bounded by their thread pools).
        self.async_sem = asyncio.Semaphore(max_concurrency)
        self.async_group_sems: Dict[str, asyncio.Semaphore] = {}
        for gname, limit in (concurrency_groups or {}).items():
            self.groups[gname] = ThreadPoolExecutor(
                max_workers=max(int(limit), 1),
                thread_name_prefix=f"actor-{actor_id[:8]}-{gname}",
            )
            self.async_group_sems[gname] = asyncio.Semaphore(
                max(int(limit), 1)
            )
        # per-caller ordered admission; seq_lock makes the cursor safe to
        # read/advance from the ring pump thread (fast dispatch) as well as
        # the event loop (slow path)
        self.seq_lock = threading.Lock()
        self.next_seq: Dict[str, int] = {}
        self.buffered: Dict[str, Dict[int, Any]] = {}
        self.num_executed = 0
        self.exiting = False

    def resolve_group(self, method, header) -> Optional[str]:
        """Group for this call: per-call override beats the method's
        declared group (reference: per-task concurrency_group_name in
        ``PushTask``). Returns None for the default group; raises KeyError
        for an unknown name."""
        gname = header.get("cg") or getattr(
            method, "_rt_concurrency_group", None
        )
        if gname is None:
            return None
        if gname not in self.groups:
            raise KeyError(gname)
        return gname

    def pool_for(self, gname: Optional[str]) -> ThreadPoolExecutor:
        return self.pool if gname is None else self.groups[gname]

    def async_sem_for(self, gname: Optional[str]) -> asyncio.Semaphore:
        return (
            self.async_sem if gname is None
            else self.async_group_sems[gname]
        )


class CoreWorker:
    def __init__(
        self,
        *,
        is_driver: bool,
        gcs_addr: Tuple[str, int],
        job_id: JobID,
        node_resources: Optional[Dict[str, float]] = None,
        node_labels: Optional[Dict[str, str]] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        head: Optional[object] = None,
        standby: bool = False,
    ):
        self.is_driver = is_driver
        # Warm worker pool membership: registered but unschedulable until
        # the head activates this node (see gcs._activate_standby).
        self.node_standby = standby
        self.gcs_addr = gcs_addr
        self.job_id = job_id
        self.worker_id = WorkerID.from_random()
        self.node_id = NodeID.from_random().hex()
        self.node_resources = dict(node_resources or {})
        # Slot marker backing zero-CPU tasks/actors (_build_resources maps
        # num_cpus=0 to 0.001 node:slot): every node advertises capacity for
        # 1000 of them.
        self.node_resources.setdefault("node:slot", 1.0)
        self.node_labels = node_labels or {}
        self.head = head  # in-process HeadService when this is the head driver

        self.loop = loop
        self.loop_thread: Optional[threading.Thread] = None
        self.server: Optional[protocol.RpcServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        self.gcs: Optional[protocol.Connection] = None
        self.peers: Dict[Tuple[str, int], protocol.Connection] = {}
        self.peer_lock: Optional[asyncio.Lock] = None

        self.ctx = SerializationContext()
        # Built lazily (see .shm): the arena name is derived from the head
        # address, which for the in-process head is only known post-start.
        self._shm: Optional[HybridShmStore] = None
        # object hex -> ("mem", header, frames) | ("shm", meta) |
        # ("dev", device spec) | ("err", exception)
        self.memory_store: Dict[str, tuple] = {}
        # Device-plane values (jax.Array, or the host-fallback ndarray a
        # pull materialized): oid hex -> value. The store entry ("dev",
        # spec) carries only metadata; the bytes live here, on device.
        self._device_objects: Dict[str, Any] = {}
        self.store_events: Dict[str, asyncio.Event] = {}
        # ownership: object hex -> {"count": local refs, "borrows": int}
        self.owned: Dict[str, dict] = {}
        self.current_task_id = threading.local()
        self.current_actor_id = threading.local()
        self.put_counter = threading.local()

        self.fn_cache: Dict[str, Any] = {}
        self.exported_fns: set = set()
        # --- submission plane batching & caching (round 10) ---
        # Pre-framed push_task spec templates: (fkey, name, retries) ->
        # packed msgpack bytes spliced into each wire message as frame 0.
        self._spec_templates: Dict[tuple, bytes] = {}
        # Receiver-side decode cache for those spec frames.
        self._spec_cache = specframe.SpecCache()
        # Function-blob push-through: blobs we can piggyback on the first
        # push of an fkey to each peer (and per-peer coverage tracking).
        self._fn_push = specframe.FnPushLedger()
        # --- reply-plane batching & arg interning (round 15) ---
        from ray_tpu._private.config import rt_config as _rtc

        # Gates cached once: these sit on per-task hot paths where an
        # env lookup per call would cost more than the feature saves.
        self._reply_batching = bool(_rtc.reply_batching)
        self._arg_interning = bool(_rtc.arg_interning)
        self._arg_intern_min = int(_rtc.arg_intern_min_bytes)
        self._arg_intern_max = int(_rtc.arg_intern_max_bytes)
        # Sender-side (peer, digest) coverage + executing-side byte-LRU
        # for interned argument frames (specframe siblings of
        # FnPushLedger/SpecCache).
        self._arg_ledger = specframe.ArgLedger()
        self._arg_intern = specframe.ArgInternCache(
            int(_rtc.arg_intern_cache_bytes)
        )
        # Connections with an open ReplyWindow (shutdown must flush them:
        # buffered results never die with the process).
        self._reply_windows: List[Any] = []
        # --- transit-plane pacing (round 16) ---
        # Adaptive in-flight push windows: per-slot AIMD congestion
        # control replacing the fixed 16x16 fan-out (gate + knobs cached
        # once — these sit in the per-chunk pack loop).
        self._push_window = bool(_rtc.push_window)
        self._pwin_initial = int(_rtc.push_window_initial)
        self._pwin_floor = int(_rtc.push_window_floor)
        self._pwin_ceiling = int(_rtc.push_window_ceiling)
        self._pwin_factor = float(_rtc.push_window_latency_factor)
        # Retired per-peer window stats (slots released by the reaper
        # fold their peak/grow/shrink counters here; bounded by peers).
        self._pwin_retired: Dict[str, dict] = {}
        # Hot-path caches: rt_config attribute reads parse the env per
        # call — far too dear for once-per-task sites (re-arm deadline,
        # dedup-cache trim horizon).
        self._push_deadline_s = float(_rtc.rpc_deadline_s)
        self._apush_horizon_s = 2.0 * self._push_deadline_s + 5.0
        self._apush_done_n = 0
        # --- driver loop scale-out (round 20) ---
        # Three planes, created in start_driver (driver-only; gates
        # cached here so hot paths pay one attribute read): the settle
        # plane moves reply splitting/future routing off the event loop,
        # the pack plane moves per-task submit accounting off the caller
        # hot path, and pusher shards move chunk packing + push pacing
        # onto dedicated loops keyed by peer address.
        # Settle auto stand-down on single-core hosts (the pusher-shard
        # auto discipline applied to the plane thread): with one CPU the
        # plane thread competes with the event loop for the GIL, so
        # every TCP reply handoff pays a scheduler round-trip with zero
        # parallel win — measured on the 1-core A/B box as 616ms median
        # reply dwell through the queued plane vs 145ms settling inline.
        # An EXPLICIT RT_DRIVER_SETTLE_THREAD setting wins either way
        # (tests pin the plane live on small hosts with =1). The pack
        # plane has no such guard: its win — O(drains) loop-enqueue
        # wakeups instead of O(tasks) — relieves the loop on any host
        # (same-box A/B: queue-wait 392ms with it vs 538ms without).
        multi_core = (os.cpu_count() or 1) >= 2
        self._settle_thread = bool(_rtc.driver_settle_thread) and (
            multi_core or "RT_DRIVER_SETTLE_THREAD" in os.environ)
        self._submit_pack = bool(_rtc.submit_pack_thread)
        self._settle_plane: Optional[specframe.SettlePlane] = None
        self._pack_plane: Optional[specframe.PlaneQueue] = None
        if is_driver:
            # Created here (not in start_driver) so BOTH driver boot
            # paths — local cluster and explicit-address connect — have
            # the planes up before _async_setup attaches connections.
            if self._settle_thread:
                self._settle_plane = specframe.SettlePlane()
            if self._submit_pack:
                self._pack_plane = specframe.PlaneQueue(
                    "rt-submit-pack", worker=self._pack_drain,
                    maxsize=4096,
                )
        self._pusher_loops: List[Any] = []
        self._pusher_threads: List[threading.Thread] = []
        self._pusher_shard_stats: List[Dict[str, int]] = []
        # Function-table miss coalescing: fkey -> shared load future, plus
        # the keys queued for the next batched kv_get_batch.
        self._fn_loading: Dict[str, asyncio.Future] = {}
        self._fn_fetch_keys: List[str] = []
        self._fn_fetch_scheduled = False
        # Deferred (batched) actor creations: aid -> _PendingActorCreate
        # while the creation has not reached the head yet.
        self._actor_creating: Dict[str, _PendingActorCreate] = {}
        self._acreate_buf: List[_PendingActorCreate] = []
        self._acreate_lock = threading.Lock()
        self._acreate_scheduled = False
        self._acreate_inflight = False
        self.leases: Dict[tuple, _LeaseSet] = {}
        self.actor_channels: Dict[str, _ActorChannel] = {}
        self.hosted_actors: Dict[str, _ActorInstance] = {}
        self.task_executor: Optional[ThreadPoolExecutor] = None
        self.num_task_slots = int(self.node_resources.get("CPU", 1)) or 1
        # Native transfer-server address, set in start() when available.
        self.xfer_addr: Optional[Tuple[str, int]] = None
        # Streaming-generator tasks this worker submitted:
        # tid hex -> {"count": total or None, "event": asyncio.Event,
        #             "produced": int, "consumed": int, "abandoned": bool,
        #             "conn": producer connection (set on first item)}
        self._task_streams: Dict[str, dict] = {}
        # Streams this worker is EXECUTING: tid hex -> {"consumed": int,
        # "event": asyncio.Event} (owner credits; bounds in-flight items)
        self._stream_credits: Dict[str, dict] = {}
        self._shutdown = False
        self._stats = {"tasks_executed": 0, "tasks_submitted": 0,
                       "spec_templates_built": 0,
                       # reply-plane economics (tests assert O(bursts))
                       "reply_windows_flushed": 0,
                       "reply_results_coalesced": 0,
                       # arg-interning economics (bytes that stayed home)
                       "arg_frames_interned": 0,
                       "arg_intern_bytes_saved": 0,
                       "arg_blobs_pushed": 0,
                       "arg_intern_miss_retries": 0,
                       # transit-plane economics (round 16; tests assert
                       # O(drains) executor wakeups, not O(messages))
                       "pump_batch_calls": 0,
                       "pump_batch_items": 0,
                       "pump_exec_wakeups": 0,
                       "push_window_shrinks": 0,
                       "push_window_waits": 0,
                       # driver loop scale-out (round 20; must stay 0 —
                       # a break means slot affinity failed and a slot's
                       # window crossed shard loops)
                       "pusher_shard_affinity_breaks": 0}
        # Submission batching: driver threads enqueue dispatch coroutines
        # here; ONE call_soon_threadsafe wakes the loop per burst instead of
        # one per task (the self-pipe write is a syscall per call).
        self._submit_buf: List[tuple] = []
        self._submit_lock = threading.Lock()
        self._submit_scheduled = False
        # Same-host shm-ring transport (native/src/ring.cc): addr -> live
        # RingConnection, or False = known-unavailable. Rings we serve (we
        # attached as side B) are kept for teardown.
        self._ring_peers: Dict[Tuple[str, int], Any] = {}
        self._ring_seq = 0
        self._served_rings: List[Any] = []
        # Lineage: producing-task specs for owned return objects so a lost
        # object can be reconstructed by resubmitting its task (reference:
        # object_recovery_manager.h:41 + reference_counter lineage pinning).
        # Byte-bounded; eviction disables reconstruction for old tasks.
        # OrderedDict: eviction pops the OLDEST entry. A plain dict's
        # next(iter(...)) rescans every tombstoned front slot per eviction
        # (O(n^2) across a long run — measured 38us/call at 450k entries);
        # popitem(last=False) is the O(1) linked-list pop.
        self._lineage: "OrderedDict[str, dict]" = OrderedDict()
        self._lineage_bytes = 0
        # runtime-env venv executors: (env key, py_modules) -> subprocess;
        # builds serialize per key so cold installs don't stall other envs
        self._env_executors: Dict[tuple, Any] = {}
        self._env_exec_keylocks: Dict[tuple, threading.Lock] = {}
        self._env_exec_lock = threading.Lock()
        self._LINEAGE_MAX_BYTES = int(
            _lineage_bytes_limit()
        )
        self._reconstructing: set = set()
        self._task_events_buf: List[dict] = []
        # GC'd ObjectRef ids awaiting a refcount decrement on the core loop
        # (deque: appends are thread-safe under the GIL; drained in one
        # callback per burst — see _install_ref_hooks).
        self._release_queue: deque = deque()
        self._release_drain_scheduled = False
        # Borrower-side refcounts for refs we deserialized but do not own:
        # hex -> {"count": local live refs, "owner": addr}. A first
        # deserialize registers a borrow with the owner; the last local
        # release returns it (reference: borrow tracking in
        # ``reference_counter.h`` — the sender's credit only pins the ref
        # for the CONTAINER's lifetime, so holders must pin their own).
        self.borrowed: Dict[str, dict] = {}
        self._borrow_queue: deque = deque()
        self._borrow_drain_scheduled = False
        from ray_tpu._private.memory_monitor import MemoryMonitor

        self._memory_monitor = MemoryMonitor()
        self.runtime_env: dict = {}
        self.pubsub_handlers: Dict[str, List[Any]] = {}
        # Correlation-id dedup for retried push_actor_task (mirrors the
        # head's _corr_replies, but thread-safe: the ring fast paths
        # execute and reply off-loop). corr -> _APUSH_WIP (executing) |
        # SyncFuture (a retry is waiting on the execution) |
        # (extras, frames) completed reply, in a bounded LRU. Only
        # successful replies are cached; failures are retried for real.
        self._apush_replies: "OrderedDict[str, Any]" = OrderedDict()
        self._apush_lock = threading.Lock()
        self._APUSH_CACHE = 256
        # Flight-recorder process label for merged cross-process traces.
        flight.set_label("driver" if is_driver else self.node_id[:8])

    @property
    def shm(self) -> HybridShmStore:
        """Session-scoped object store: every process on this machine maps the
        same native arena, named after the head address."""
        if self._shm is None:
            port = self.gcs_addr[1]
            arena = f"/rt_arena_{port}_{os.getuid()}" if port else None
            self._shm = HybridShmStore(arena)
            self._shm.spill_handler = self._spill_for_space
        return self._shm

    def _spill_for_space(self, need: int) -> int:
        """Free arena space by spilling this process's oldest sealed objects
        to disk (reference: ``local_object_manager.h:144`` SpillObjects).
        Returns bytes freed. Any process may spill its own objects — the
        arena's pin/delete protocol makes concurrent readers safe, and the
        head's directory entry is updated so every other process finds the
        disk copy on its next lookup."""
        arena = self._shm.arena if self._shm is not None else None
        if arena is None:
            return 0
        # Gather the batch first (oldest sealed objects up to `need`),
        # then write it in PARALLEL on the spill IO pool (reference:
        # SpillObjects batches; IO workers run the writes).
        batch = []
        batched = 0
        for hex_ in list(arena._created):  # insertion order = oldest first
            if batched >= need:
                break
            frames = arena.get_frames(hex_, {})
            if frames is None:
                continue
            batch.append((hex_, frames))
            batched += sum(len(f) for f in frames)
        metas = self._shm.spill.spill_many(batch)
        freed = 0
        regs = []
        for (hex_, _frames), meta in zip(batch, metas):
            if meta is None:
                continue  # write failed (storage unavailable); keep in arena
            arena.free(hex_)
            freed += meta["size"]
            # "addr" routes readers that cannot open the uri (other hosts,
            # different backend) to this worker's RPC service, which
            # serves the spilled bytes. "owner" keeps the directory entry
            # attributable after the spill flips its kind (leak detection
            # matches on it).
            meta = dict(
                meta, node=self.node_id,
                addr=list(self.addr) if self.addr else None,
                owner=list(self.addr or ()),
            )
            if hex_ in self.memory_store:
                self.memory_store[hex_] = ("shm", meta)
            regs.append((hex_, meta))
        # Read pins ride the frame views inside `batch`; dropping it lets
        # the finalizers release them so the freed blocks actually reclaim.
        del batch
        if regs:
            def register():
                for hex_, meta in regs:
                    try:
                        self.gcs.notify(
                            "object_register", {"oid": hex_, "meta": meta}
                        )
                    except protocol.ConnectionLost:
                        return
            try:
                self.loop.call_soon_threadsafe(register)
            except RuntimeError:
                pass
            logger.info("spilled %d object(s), %.1f MB freed",
                        len(regs), freed / 1e6)
        return freed

    # ------------------------------------------------------------------ setup

    def start_driver(self):
        """Start core loop thread + service and connect to the head."""
        ready = threading.Event()

        def runner():
            self.loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self.loop)
            self.loop.run_until_complete(self._async_setup())
            ready.set()
            self.loop.run_forever()

        self.loop_thread = threading.Thread(
            target=runner, name="rt-core-loop", daemon=True
        )
        self.loop_thread.start()
        if not ready.wait(timeout=30):
            raise RuntimeError("core loop failed to start")
        self._start_pusher_shards()
        self._install_ref_hooks()

    def _start_pusher_shards(self):
        """Round 20: spin up the sharded pusher loops (driver-only).
        Lease slots hash by peer address onto these loops in
        _pump_leases; everything a pusher must touch on the MAIN loop
        (peer/ring connect, task-reply application, slot bookkeeping)
        marshals across explicitly in _slot_pusher."""
        from ray_tpu._private.config import rt_config

        n = int(rt_config.pusher_loop_shards)
        if n < 0:
            n = min(2, (os.cpu_count() or 1) - 1)
        for i in range(max(n, 0)):
            ready = threading.Event()
            holder: Dict[str, Any] = {}

            def runner(ready=ready, holder=holder):
                loop = asyncio.new_event_loop()
                asyncio.set_event_loop(loop)
                holder["loop"] = loop
                ready.set()
                loop.run_forever()

            t = threading.Thread(
                target=runner, name=f"rt-pusher-{i}", daemon=True
            )
            t.start()
            if not ready.wait(timeout=10):
                logger.warning("pusher shard %d failed to start", i)
                continue
            self._pusher_loops.append(holder["loop"])
            self._pusher_threads.append(t)
            self._pusher_shard_stats.append({"chunks": 0, "tasks": 0})

    @staticmethod
    def _tune_gc():
        """Freeze the post-import heap out of the cyclic GC and raise the
        collection cadence (reference behavior: the C++ core never pays a
        tracing-GC pause on the task path; CPython must be told not to).
        With millions of live refs/lineage records, default thresholds make
        full collections O(heap) pauses every few thousand allocations —
        measured 1.33x sustained submission throughput on the queued-1M
        leg. Cycles still collect, just less often. RT_GC_TUNING=0 opts
        out."""
        from ray_tpu._private.config import rt_config

        if not rt_config.gc_tuning:
            return
        import gc

        gc.collect()
        gc.freeze()
        gc.set_threshold(50_000, 20, 20)

    async def _async_setup(self):
        self._tune_gc()
        self.peer_lock = asyncio.Lock()
        self.ring_lock = asyncio.Lock()
        if self.is_driver:
            # Create the session arena now so the *driver* owns it: the driver
            # is the one process guaranteed to run close_all at shutdown, so
            # the /dev/shm segment gets unlinked (workers die by SIGTERM).
            _ = self.shm
        self.task_executor = ThreadPoolExecutor(
            max_workers=max(self.num_task_slots, 4),
            thread_name_prefix="rt-task",
        )
        self.server = protocol.RpcServer(self._handle_rpc)
        self.addr = await self.server.start()
        # Native object-transfer server (reference: the object_manager data
        # plane, ``object_manager.h:128``): serves this worker's shm-backed
        # objects over TCP so remote hosts pull bulk payloads through C++
        # instead of the Python RPC plane. Binds the SAME host the RPC plane
        # advertises (no wider), and starts in an executor — the first call
        # may compile the library and must not stall the event loop.
        from ray_tpu._private.config import rt_config
        if rt_config.native_xfer:
            try:
                from ray_tpu.native import xfer as native_xfer

                port = await asyncio.get_running_loop().run_in_executor(
                    None, native_xfer.start_server, self.addr[0]
                )
                if port:
                    self.xfer_addr = (self.addr[0], port)
            except Exception:
                logger.debug("native xfer server unavailable", exc_info=True)
        # Object-free fan-out: evict borrowed copies when the owner frees.
        self.pubsub_handlers.setdefault("object_free", []).append(
            lambda data, frames: self._evict_freed(data.get("oids", []))
        )
        # Demand-driven lease return: the head asks when a placement can't
        # fit; cached idle slots go back NOW instead of after the reaper's
        # idle window (otherwise a task burst pins node CPUs for ~1s and a
        # placement-group create right behind it stalls).
        self.pubsub_handlers.setdefault("lease_reclaim", []).append(
            lambda data, frames: self._reclaim_idle_leases()
        )
        # Live worker-log echo (reference: print_worker_logs — remote task
        # prints appear on the driver, prefixed with worker/node). Job-
        # scoped: lines from other jobs' workers stay out of this driver's
        # terminal. RT_LOG_TO_DRIVER=0 silences the echo (files + rt logs
        # still capture everything).
        if self.is_driver and os.environ.get("RT_LOG_TO_DRIVER", "1") != "0":
            from ray_tpu._private.log_monitor import print_worker_logs

            my_job = self.job_id.hex() if self.job_id else ""

            def _echo(data, frames):
                # Own-job lines, plus lines from shared workers (spawned
                # outside any driver job — rt start / autoscaler nodes).
                if data.get("shared") or data.get("job_id") in ("", my_job):
                    print_worker_logs(data)

            self.pubsub_handlers.setdefault("worker_logs", []).append(_echo)
        await self._connect_gcs()
        spawn_logged(self.loop, self._task_event_flusher(),
                     "worker.task_event_flusher")
        if not self.is_driver:
            from ray_tpu._private.config import rt_config

            if rt_config.oom_kill:
                threading.Thread(
                    target=self._pressure_killer_loop, daemon=True,
                    name="rt-oomkill",
                ).start()

    async def _connect_gcs(self):
        """Connect + subscribe + (re-)register with the head. Shared by
        startup and the head-restart rejoin path (reference: raylets
        reconnect to a restarted GCS and re-register,
        ``gcs_init_data.cc`` replay)."""
        from ray_tpu._private.config import rt_config

        tmo = float(rt_config.rpc_deadline_s)
        self.gcs = await protocol.connect(
            self.gcs_addr, self._handle_rpc, name="gcs-client"
        )
        self.gcs.settle_plane = self._settle_plane
        self.gcs.on_close = self._on_gcs_lost
        # Every registration call is deadline-bounded: a head that accepts
        # the TCP connection but drops replies must kick us back into the
        # reconnect loop, not wedge it forever mid-handshake.
        # Subscribe to EVERY channel with a registered handler (plus the
        # built-ins): a restarted head has an empty subscriber table, so
        # reconnect must restore late-registered channels too (e.g. serve
        # replica-change pushes), not just the boot-time set.
        for channel in {"object_free", "lease_reclaim",
                        *self.pubsub_handlers}:
            await asyncio.wait_for(
                self.gcs.call("subscribe", {"channel": channel}), tmo
            )
        # Cluster-wide config overrides (init(_system_config=...)) live in
        # the head KV; every process applies them at (re)connection —
        # the reference passes _system_config on raylet command lines.
        try:
            hh, frames = await asyncio.wait_for(
                self.gcs.call(
                    "kv_get", {"ns": "__rt", "key": "system_config"}
                ),
                tmo,
            )
            if hh.get("found") and frames:
                import json as _json

                from ray_tpu._private.config import rt_config

                rt_config.apply_system_config(_json.loads(frames[0]))
        except (asyncio.TimeoutError, protocol.RpcError, ValueError) as e:
            logger.debug("system-config fetch failed, using defaults: %s", e)
        if self.is_driver:
            await asyncio.wait_for(
                self.gcs.call(
                    "register_job", {"job_id": self.job_id.hex()}
                ),
                tmo,
            )
        else:
            hosted = [
                {"actor_id": aid, **getattr(inst, "public_meta", {})}
                for aid, inst in self.hosted_actors.items()
                if not inst.exiting
            ]
            reg = {
                "node_id": self.node_id,
                "addr": list(self.addr),
                "resources": self.node_resources,
                "labels": self.node_labels,
                "hosted_actors": hosted,
            }
            if self.node_standby:
                # Warm pool: registered but unschedulable until activated.
                # Re-registration after a head restart keeps the flag only
                # if nothing was scheduled here yet (hosted actors imply
                # the head activated us before it restarted).
                reg["standby"] = not hosted
            await asyncio.wait_for(
                self.gcs.call("register_node", reg),
                tmo,
            )

    def _on_gcs_lost(self, conn):
        if self._shutdown or self.loop is None:
            return
        try:
            self.loop.call_soon_threadsafe(
                lambda: spawn_logged(self.loop, self._reconnect_gcs(),
                                     "worker.reconnect_gcs")
            )
        except RuntimeError:
            pass

    async def _reconnect_gcs(self):
        """Head connection lost: retry with backoff so a restarted head
        re-adopts this process (live-cluster rejoin). Cached leases are
        dropped first — a restarted head has no memory of granting them,
        and using them would dispatch onto capacity the new head already
        counts as free."""
        if self._shutdown:
            return
        # Single reconnect loop at a time: a connect that succeeds but dies
        # during subscribe fires on_close again; a second loop would race
        # this one and leak a registered connection.
        if getattr(self, "_gcs_reconnecting", False):
            return
        if self.gcs is not None and not self.gcs._closed:
            return  # already reconnected
        self._gcs_reconnecting = True
        try:
            await self._reconnect_gcs_inner()
        finally:
            self._gcs_reconnecting = False

    async def _reconnect_gcs_inner(self):
        from ray_tpu._private.config import rt_config

        for lease_set in self.leases.values():
            lease_set.slots = [s for s in lease_set.slots if s.busy > 0]
        deadline = time.monotonic() + float(
            rt_config.head_reconnect_s
        )
        delay = 0.25
        while not self._shutdown and time.monotonic() < deadline:
            try:
                await self._connect_gcs()
                logger.info(
                    "reconnected to head at %s:%d", *self.gcs_addr
                )
                return
            except (asyncio.TimeoutError, OSError, protocol.ConnectionLost,
                    protocol.RpcError):
                # A handshake that died mid-way (e.g. subscribe deadline)
                # leaves an open half-registered connection: close it so
                # the next attempt starts clean instead of leaking one
                # connection per retry.
                if self.gcs is not None and not self.gcs._closed:
                    await self.gcs.close()
                await asyncio.sleep(delay)
                delay = min(delay * 2, 2.0)
        if not self._shutdown:
            logger.warning(
                "head at %s:%d did not come back within the rejoin window",
                *self.gcs_addr,
            )

    def _install_ref_hooks(self):
        worker = self

        def release(object_id: ObjectID):
            # Coalesce: a container GC can drop 10k+ refs back-to-back (one
            # __del__ per element); one loop callback per ref floods the
            # event loop for seconds and starves control RPCs (observed:
            # 150x pg-churn collapse right after a 10k-ref get). Queue the
            # ObjectID and schedule a single drain per burst — the hex
            # conversion happens on the loop thread, off the GC'ing
            # thread's critical path.
            if worker._shutdown or worker.loop is None:
                return
            worker._enqueue_ref_op(("dec", object_id))

        def on_deserialize(ref: ObjectRef):
            # A materialized ref must pin itself: the sender's credit dies
            # with the containing object, and the user may outlive it
            # (e.g. shuffle piece refs returned from a map task).
            if worker._shutdown or worker.loop is None:
                return
            owner = tuple(ref.owner_address or ())
            if not owner:
                return
            worker._borrow_queue.append((ref.id().hex(), owner))
            if worker._borrow_drain_scheduled:
                return
            worker._borrow_drain_scheduled = True
            try:
                worker.loop.call_soon_threadsafe(worker._drain_borrows)
            except RuntimeError:
                worker._borrow_drain_scheduled = False

        def on_deserialize_batch(refs):
            # One queue entry + one wakeup for a whole deserialized value,
            # however many refs it nests. Hex/owner-tuple bookkeeping for
            # every ref moves to the loop-side drain, off the deserializing
            # thread (the get-10k-refs hot path).
            if worker._shutdown or worker.loop is None:
                return
            worker._borrow_queue.append((None, refs))
            if worker._borrow_drain_scheduled:
                return
            worker._borrow_drain_scheduled = True
            try:
                worker.loop.call_soon_threadsafe(worker._drain_borrows)
            except RuntimeError:
                worker._borrow_drain_scheduled = False

        ObjectRef._release_hook = release
        ObjectRef._deserialize_hook = on_deserialize
        ObjectRef._deserialize_batch_hook = on_deserialize_batch

    def run_sync(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except SyncTimeoutError:
            # The caller is giving up: the scheduled coroutine must not
            # keep running (and holding store events, RPC futures, borrow
            # pins) as an orphan on the core loop. cancel() no-ops if the
            # coroutine won the race and completed.
            fut.cancel()
            raise

    async def _head_call(self, method, extras=None, frames=(), *,
                         timeout=None, retries=None, corr=False):
        """Head RPC with a real per-attempt deadline and jittered retries.

        A dropped reply used to hang the calling verb forever (the bare
        ``gcs.call`` future only resolves on reply or connection
        teardown); here each attempt is bounded by ``timeout``
        (default ``rt_config.rpc_deadline_s``) and timeouts / connection
        losses / "unavailable" errors re-issue up to ``retries`` times
        with jittered backoff (reference: retryable_grpc_client.cc
        retrying UNAVAILABLE under a deadline).

        ``corr=True`` attaches a correlation id shared by every attempt of
        this logical request: the head replays the original reply for a
        retry whose predecessor was applied but unacknowledged, so
        non-idempotent verbs (lease, create_actor, create_pg) never
        double-apply.
        """
        from ray_tpu._private.config import rt_config

        if timeout is None:
            timeout = float(rt_config.rpc_deadline_s)
        if retries is None:
            retries = int(rt_config.rpc_retries)
        extras = dict(extras or {})
        if corr:
            extras["corr"] = os.urandom(8).hex()
        fl = flight.ENABLED
        if fl and "corr" not in extras:
            # One flight id for every attempt of this logical request: the
            # head-side dispatch span joins on it.
            extras["fid"] = flight.next_id()
        fl_cid = extras.get("corr") or extras.get("fid")
        retry = Backoff(base=0.05, cap=2.0)
        attempt = 0
        while True:
            if fl:
                fl_t0 = time.monotonic()
            try:
                conn = self.gcs
                if conn is None or conn._closed:
                    raise protocol.ConnectionLost("head connection down")
                res = await asyncio.wait_for(
                    conn.call(method, extras, list(frames)), timeout
                )
                if fl:
                    flight.record(
                        f"head.{method}", fl_cid, "client", fl_t0,
                        time.monotonic(), 0,
                        "ok" if attempt == 0 else f"ok:attempt{attempt + 1}",
                    )
                return res
            except asyncio.TimeoutError as e:
                last: Exception = e
                if fl:
                    flight.record(f"head.{method}", fl_cid, "client",
                                  fl_t0, time.monotonic(), 0, "timeout")
            except (protocol.ConnectionLost, OSError) as e:
                last = e
                if fl:
                    flight.record(f"head.{method}", fl_cid, "client",
                                  fl_t0, time.monotonic(), 0,
                                  f"error:{type(e).__name__}")
            except protocol.RpcError as e:
                if fl:
                    flight.record(f"head.{method}", fl_cid, "client",
                                  fl_t0, time.monotonic(), 0,
                                  f"error:{type(e).__name__}")
                # Application errors are terminal; only the transient
                # unavailability class is worth re-issuing.
                if getattr(e, "code", None) != "unavailable":
                    raise
                last = e
            if attempt >= retries or self._shutdown:
                if isinstance(last, asyncio.TimeoutError):
                    raise protocol.RpcError(
                        f"head rpc {method!r} exceeded its {timeout}s "
                        f"deadline {attempt + 1} time(s)", code="deadline",
                    )
                raise last
            attempt += 1
            await asyncio.sleep(retry.next_delay())

    # ------------------------------------------------------------ connections

    async def get_peer(self, addr: Tuple[str, int]) -> protocol.Connection:
        addr = tuple(addr)
        conn = self.peers.get(addr)
        if conn is not None and not conn._closed:
            return conn
        async with self.peer_lock:
            conn = self.peers.get(addr)
            if conn is not None and not conn._closed:
                return conn
            conn = await protocol.connect(addr, self._handle_rpc, name=f"peer-{addr}")
            conn.settle_plane = self._settle_plane
            self.peers[addr] = conn
            return conn

    # ----------------------------------------------------- ring transport

    async def get_ring(self, addr):
        """Same-host shm-ring transport to the peer at ``addr``; None when
        unavailable (different host, native lib missing, or peer refused).
        The hot task/actor push path prefers this over TCP (reference: the
        C++ core worker's native submission plane,
        ``task_submission/normal_task_submitter.h:86``)."""
        from ray_tpu.native import ring as ring_mod

        addr = tuple(addr)
        cached = self._ring_peers.get(addr)
        if cached is False:
            return None
        if cached is not None and not cached._closed:
            return cached
        if (
            not ring_mod.available()
            or self.addr is None
            or addr[0] != self.addr[0]  # other host: TCP plane
        ):
            return None
        from ray_tpu._private.ringconn import RingConnection

        async with self.ring_lock:  # NOT peer_lock: get_peer acquires that
            cached = self._ring_peers.get(addr)
            if cached is False:
                return None
            if cached is not None and not cached._closed:
                return cached
            conn = await self.get_peer(addr)
            self._ring_seq += 1
            name = f"/rtring_{os.getpid()}_{self._ring_seq}"
            try:
                nring = ring_mod.NativeRing(name, create=True)
            except (OSError, RuntimeError):
                self._ring_peers[addr] = False
                return None
            try:
                await conn.call("ring_attach", {"name": name})
            except (protocol.RpcError, protocol.ConnectionLost):
                nring.detach()
                self._ring_peers[addr] = False
                return None
            rc = RingConnection(
                nring, self.loop, handler=self._handle_rpc,
                name=f"ring-{addr[1]}",
            )
            rc.settle_plane = self._settle_plane
            self._ring_peers[addr] = rc
            # Peer-process death is detected by the TCP conn: closing it
            # closes the ring too (the ring itself has no liveness probe).
            prev = conn.on_close

            def chained(c, _rc=rc, _prev=prev):
                _rc._teardown()
                if _prev is not None:
                    _prev(c)

            conn.on_close = chained
            return rc

    async def rpc_ring_attach(self, h, frames, conn):
        """Peer asks us to serve its shm ring (it created the segment)."""
        from ray_tpu.native import ring as ring_mod

        if not ring_mod.available():
            raise protocol.RpcError("native ring unavailable", code="no_ring")
        from ray_tpu._private.ringconn import RingConnection

        try:
            nring = ring_mod.NativeRing(h["name"], create=False)
        except OSError as e:
            raise protocol.RpcError(f"ring attach failed: {e}")
        rc = RingConnection(
            nring, asyncio.get_running_loop(), handler=self._handle_rpc,
            fast_dispatch=self._ring_fast_dispatch,
            fast_batch=self._ring_fast_dispatch_batch,
            name=f"ringsrv-{h['name']}",
        )
        # keep for teardown; prune dead ones so reconnect churn stays bounded
        self._served_rings = [
            r for r in self._served_rings if not r._closed
        ] + [rc]
        prev = conn.on_close

        def chained(c, _rc=rc, _prev=prev):
            _rc._teardown()
            if _prev is not None:
                _prev(c)

        conn.on_close = chained
        return {}, []

    def _ring_fast_dispatch(self, h, frames, rconn) -> bool:
        """Pump-thread fast path: a plain task whose function is cached and
        whose args carry no refs executes straight on the task executor —
        no event loop on either decode, execute, or (small-result) reply.
        Returns False to route anything non-trivial to the slow path, whose
        semantics (arg fetch, runtime envs, OOM rejection, streaming) are
        authoritative. Actor pushes get the same treatment when they are
        the caller's next in-order call (``_ring_actor_fast_dispatch``)."""
        if h.get("m") == "push_actor_task":
            return self._ring_actor_fast_dispatch(h, frames, rconn)
        if h.get("m") == "mrack":
            # Reply-window ack: clock the next coalesced flush right on
            # the pump thread (no loop hop — the flush itself is a ring
            # send this thread can make).
            w = getattr(rconn, "_rt_reply_window", None)
            if w is not None:
                w.on_ack()
            return True
        if h.get("m") != "push_task":
            return False
        if self.node_standby:
            # Mirrors rpc_push_task: work arriving over the ring fast path
            # also means the head activated this node — a later
            # re-registration must not claim standby.
            self.node_standby = False
        if "sp" in h or "fb" in h or "ai" in h or "aib" in h:
            # Pre-framed spec / piggybacked function / interned args:
            # expand here so the eligibility gates below see the FULL
            # header (a False return routes the ORIGINAL message to the
            # slow path, which expands again — cache hits both times).
            try:
                h, frames = self._expand_task_header(h, frames)
            except protocol.RpcError:
                # Interned-arg miss: the slow path raises it as the typed
                # error the pusher recovers from (blob re-sent).
                return False
        if (
            h.get("nret", 1) < 1          # streaming (-1) stays on the loop
            or h.get("argrefs")
            or h.get("borrows")
            or h.get("renv")
            or h.get("trace")
        ):
            return False
        fn = self.fn_cache.get(h["fkey"])
        if fn is None:
            return False
        if self._memory_monitor.is_pressing():
            return False  # slow path raises the structured oom rejection
        ex = self.task_executor
        if ex is None:
            return False
        ex.submit(self._ring_execute_task, fn, h, frames, rconn,
                  t_arr=time.monotonic())
        return True

    def _ring_fast_dispatch_batch(self, items, rconn):
        """Pump-thread fast path for a WHOLE batch wire message: the
        fast-eligible plain tasks in it are split into ≤ num_task_slots
        contiguous chunks, each chunk executing sequentially on one
        executor thread and answering with ONE batched reply — per-task
        submit/encode/send amortizes across the chunk while real
        parallelism still matches the node's task slots. Everything not
        eligible (actor pushes, refs, runtime envs, uncached functions) is
        returned for the per-item fast/slow paths, whose semantics are
        authoritative."""
        t_arr = time.monotonic()
        # Transit economics (tests assert O(drains) executor wakeups):
        # one call here per pump drain when pump_batch_drain is on, one
        # per batch wire message when off.
        self._stats["pump_batch_calls"] += 1
        self._stats["pump_batch_items"] += len(items)
        ex = self.task_executor
        if ex is None or self._memory_monitor.is_pressing():
            return items
        if self.node_standby and any(
            h.get("m") in ("push_task", "push_actor_task") for h, _ in items
        ):
            # Same activation signal as the per-item paths (which a fully
            # fast-path batch would never reach).
            self.node_standby = False
        eligible = []
        leftovers = []
        # Consecutive same-actor calls from one caller execute as ONE pool
        # submission with one batched reply (the n:n actor-burst shape);
        # anything the run path declines falls through per-item.
        items = self._coalesce_actor_runs(items, rconn)
        for h, frames in items:
            if h.get("m") == "push_task" and (
                "sp" in h or "fb" in h or "ai" in h or "aib" in h
            ):
                # Expanded view for eligibility + execution; leftovers keep
                # the ORIGINAL message (the slow path re-expands, cached).
                try:
                    eh, ef = self._expand_task_header(h, frames)
                except protocol.RpcError:
                    # Interned-arg miss: slow path raises the typed error.
                    leftovers.append((h, frames))
                    continue
            else:
                eh, ef = h, frames
            if (
                eh.get("m") != "push_task"
                or eh.get("nret", 1) < 1
                or eh.get("argrefs")
                or eh.get("borrows")
                or eh.get("renv")
                or eh.get("trace")
            ):
                leftovers.append((h, frames))
                continue
            fn = self.fn_cache.get(eh["fkey"])
            if fn is None:
                leftovers.append((h, frames))
                continue
            eligible.append((fn, eh, ef))
        if not eligible:
            return leftovers
        if self._reply_batching:
            # Claim the whole chunk's corr ids in ONE dedup pass;
            # duplicates of completed tasks answer as one replayed
            # multi-result frame right here on the pump thread.
            eligible = self._ring_claim_chunk(eligible, rconn)
            if not eligible:
                return leftovers
        # Work-stealing queue, not static chunks: N executor loops pop one
        # task at a time, so a slow task never serializes the fast tasks
        # behind it (head-of-line blocking) while sibling threads idle —
        # each loop still coalesces ITS completions into one batched reply.
        dq: deque = deque(eligible)
        nloops = min(len(eligible), max(self.num_task_slots, 1))
        for c in range(nloops):
            try:
                ex.submit(self._ring_execute_queue, dq, rconn, t_arr)
                self._stats["pump_exec_wakeups"] += 1
            except RuntimeError:
                # Executor shut down. Loops already submitted will drain
                # the whole queue, so leftovers only exist when NONE got
                # in; re-dispatching otherwise would double-execute.
                if c == 0:
                    # Release the dispatch-time corr claims: the slow
                    # path these re-route to runs its own dedup, and a
                    # stale WIP entry would wrongly attach it.
                    with self._apush_lock:
                        for _fn, h, _fr in dq:
                            corr = h.get("corr")
                            if (corr and self._apush_replies.get(corr)
                                    is _APUSH_WIP):
                                self._apush_replies.pop(corr, None)
                    leftovers.extend((h, fr) for _fn, h, fr in dq)
                    dq.clear()
                break
        return leftovers

    def _ring_claim_chunk(self, eligible, rconn):
        """Claim a fast-path chunk's corr ids in one dedup pass (pump
        thread). Items claimed "mine" return for execution; duplicates
        answer here — completed outcomes replay as ONE coalesced frame,
        in-flight twins attach to the execution's own reply."""
        corrs = [h.get("corr") for _fn, h, _f in eligible]
        if not any(corrs):
            # Pusher didn't arm the corr plane (mixed gates): nothing to
            # claim, nothing can replay.
            return eligible
        states = self._apush_begin_many(corrs)
        keep = []
        subs: List[dict] = []
        counts: List[int] = []
        flat: List[bytes] = []
        for item, (state, obj) in zip(eligible, states):
            if state == "mine":
                keep.append(item)
            elif state == "replay":
                extras, fr = obj
                subs.append({"i": item[1]["i"], **dict(extras)})
                counts.append(len(fr))
                flat.extend(fr)
            else:  # wait
                self._attach_dup_reply(obj, item[1]["i"], rconn)
        if subs:
            rconn.send_reply_batch(subs, counts, list(flat))
        return keep

    def _coalesce_actor_runs(self, items, rconn):
        """Group consecutive eligible actor calls (same actor, same
        caller, in-seq, plain sync method on a serial group-less actor)
        into single pool submissions with ONE batched reply each; returns
        the items NOT consumed by a run. Per-caller FIFO is preserved:
        a run executes sequentially on the actor's serial pool exactly as
        the per-item submissions would have."""
        out = []
        i = 0
        n = len(items)
        while i < n:
            h, fr = items[i]
            if h.get("m") != "push_actor_task":
                out.append(items[i])
                i += 1
                continue
            run = [items[i]]
            j = i + 1
            while j < n:
                h2 = items[j][0]
                if (
                    h2.get("m") != "push_actor_task"
                    or h2.get("aid") != h.get("aid")
                    or h2.get("caller") != h.get("caller")
                ):
                    break
                run.append(items[j])
                j += 1
            if len(run) >= 2 and self._try_submit_actor_run(run, rconn):
                i = j
            else:
                # Whole run falls to per-item dispatch: retrying suffixes
                # head-by-head would rescan the same headers O(n^2) on the
                # pump thread.
                out.extend(run)
                i = j
        return out

    @staticmethod
    def _actor_fast_inst_ok(inst) -> bool:
        """Instance-level fast-path gates shared by the per-item dispatch
        and the coalesced-run dispatch (they must never diverge — a gate
        added to one but not the other silently changes semantics
        depending on whether calls arrive as a burst)."""
        return not (
            inst is None or inst.exiting or inst.max_concurrency != 1
            or inst.groups
        )

    @staticmethod
    def _actor_fast_header_ok(h) -> bool:
        """Header-level fast-path gates (same sharing contract)."""
        return not (
            h.get("nret", 1) != 1
            or h.get("argrefs")
            or h.get("borrows")
            or h.get("trace")
            or h.get("cg")
            or h.get("method") == "__rt_apply__"
        )

    def _exec_actor_call(self, inst, method, h, frames):
        """Execute one admitted actor call: deserialize, set task context,
        run. Returns (ok, result) or the string "exited" after performing
        the clean-exit protocol (actor table removal + head notify) for
        SystemExit/exit_actor. Shared execution core of the per-item and
        coalesced fast paths."""
        try:
            arg_slots, plain, kwargs = self.ctx.deserialize_frames(frames)
            args = [plain[i] for _k, i in arg_slots]  # eligibility: no refs
            self.current_task_id.value = TaskID.from_hex(h["tid"])
            self.current_actor_id.value = h["aid"]
            self.put_counter.value = 0
            try:
                return True, method(*args, **kwargs)
            except SystemExit:
                self.hosted_actors.pop(h["aid"], None)
                inst.exiting = True
                self.gcs.notify(
                    "actor_exited",
                    {"actor_id": h["aid"], "clean": True,
                     "reason": "exit_actor"},
                )
                return "exited"
            except Exception as e:
                return False, (e, traceback.format_exc())
        except Exception as e:
            return False, (e, traceback.format_exc())

    def _try_submit_actor_run(self, run, rconn) -> bool:
        """Admit a whole same-(actor, caller) run atomically: every call
        must pass the per-item fast-path gates AND the seqs must be
        exactly consecutive from the caller's cursor. Any mismatch rejects
        the WHOLE run (per-item dispatch handles it) — partial admission
        would reorder."""
        h0 = run[0][0]
        inst = self.hosted_actors.get(h0.get("aid"))
        if not self._actor_fast_inst_ok(inst):
            return False
        if self._memory_monitor.is_pressing():
            return False  # same pressure gate as the per-item path
        methods = []
        for h, _fr in run:
            if not self._actor_fast_header_ok(h) or h.get("seq", 0) <= 0:
                return False
            method = getattr(inst.instance, h.get("method", ""), None)
            if method is None or asyncio.iscoroutinefunction(method):
                return False
            methods.append(method)
        caller = h0.get("caller", "")
        with inst.seq_lock:
            nxt = inst.next_seq.setdefault(caller, 1)
            for k, (h, _fr) in enumerate(run):
                if h.get("seq") != nxt + k:
                    return False
            try:
                inst.pool.submit(
                    self._ring_execute_actor_chunk, inst, methods, run,
                    rconn,
                )
            except RuntimeError:
                return False  # pool shut down (actor being killed)
            inst.next_seq[caller] = nxt + len(run)
            ev = inst.buffered.get(caller, {}).pop(nxt + len(run), None)
        if ev is not None:
            try:
                self.loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass
        return True

    def _ring_execute_actor_chunk(self, inst, methods, run, rconn):
        """Execute an admitted actor run sequentially on the actor's
        serial pool; small results coalesce into one batched reply.
        SystemExit (exit_actor) mid-run follows the per-item protocol for
        that call and fails the remainder the way per-item dispatch would
        have (actor exiting -> ActorMissing)."""
        subs = []
        counts = []
        out: List[bytes] = []
        exited = False
        for method, (h, frames) in zip(methods, run):
            corr = h.get("corr")
            state, obj = self._apush_begin(corr)
            if state != "mine":
                # Duplicate delivery inside an admitted run (should not
                # pass the consecutive-seq gate, but replay is always
                # safe; "wait" twins reply from their own path).
                if state == "replay":
                    extras, fr = obj
                    subs.append({"i": h["i"], **dict(extras)})
                    counts.append(len(fr))
                    out.extend(fr)
                continue
            # inst.exiting: a concurrent ray-kill must stop the rest of
            # the run the way it would have cancelled still-queued
            # per-item futures.
            if exited or inst.exiting:
                self._apush_fail(
                    corr, protocol.RpcError("ActorMissing: actor exited")
                )
                subs.append(
                    {"i": h["i"], "e": "ActorMissing: actor exited"}
                )
                counts.append(0)
                continue
            t0 = time.time()
            fl = flight.ENABLED
            if fl:
                tm0 = time.monotonic()
            res = self._exec_actor_call(inst, method, h, frames)
            if fl:
                taskpath.record_phase(
                    "exec", h["tid"], tm0, time.monotonic(),
                    fn=h["method"], phase="exec",
                )
            if res == "exited":
                self._apush_fail(
                    corr, protocol.RpcError("ActorMissing: actor exited")
                )
                subs.append(
                    {"i": h["i"], "e": "ActorMissing: actor exited"}
                )
                counts.append(0)
                exited = True
                continue
            ok, result = res
            try:
                rets, out_frames, big = self._package_result_parts(
                    h, ok, result
                )
            except Exception as e:
                logger.exception("actor chunk reply packaging failed")
                self._apush_fail(corr, e)
                subs.append(
                    {"i": h["i"], "e": f"reply packaging failed: {e!r}"}
                )
                counts.append(0)
                continue
            finally:
                inst.num_executed += 1
                self._record_task_event({
                    "task_id": h["tid"], "name": h["method"],
                    "type": "ACTOR_TASK", "actor_id": h["aid"],
                    "corr": h.get("corr"),
                    "state": "FINISHED" if ok else "FAILED",
                    "start_time": t0, "end_time": time.time(),
                    "node_id": self.node_id,
                })
            if big or self._reply_batching:
                # big → individual shm-registration path; small with
                # reply batching on → the connection's shared reply
                # window (cross-run coalescing + ack clocking).
                self._ring_reply_packaged(h, rets, out_frames, big, rconn)
            else:
                self._apush_done(corr, {"rets": rets}, out_frames)
                subs.append({"i": h["i"], "rets": rets})
                counts.append(len(out_frames))
                out.extend(out_frames)
        if subs:
            rconn.send_reply_batch(subs, counts, out)

    def _ring_execute_one(self, fn, h, frames):
        """The fast-path per-task execution core, shared by the batched and
        per-item paths (they must never diverge): deserialize ref-free
        args, set task-locals, run, two-level exception guard."""
        if faultpoints.ACTIVE:
            # delay/crash only (catalog): both behave identically to the
            # slow path's hook, so a chaos spec means the same thing on
            # either transport.
            faultpoints.fire("worker.task.exec")
        try:
            arg_slots, plain, kwargs, sep = self._decode_arg_frames(h, frames)
            args = [sep[i] if k == "sv" else plain[i]
                    for k, i in arg_slots]  # eligibility: no refs
            self.current_task_id.value = TaskID.from_hex(h["tid"])
            self.current_actor_id.value = None
            self.put_counter.value = 0
            try:
                return True, fn(*args, **kwargs)
            except Exception as e:
                return False, (e, traceback.format_exc())
        except Exception as e:
            return False, (e, traceback.format_exc())

    def _ring_finish_task(self, h, ok, t0):
        self._stats["tasks_executed"] += 1
        self._record_task_event({
            "task_id": h["tid"], "name": h.get("name") or h["fkey"],
            "type": "NORMAL_TASK",
            "state": "FINISHED" if ok else "FAILED",
            "start_time": t0, "end_time": time.time(),
            "node_id": self.node_id,
        })

    def _ring_execute_queue(self, dq: deque, rconn, t_arr=None):
        """One executor loop of the batched fast path: pop tasks until the
        shared queue drains. With reply batching on, each completion goes
        straight into the connection's self-clocking ReplyWindow — the
        first result flushes the moment it exists and chunk-mates ride
        the in-flight frame's ack, instead of every result waiting for
        the WHOLE queue drain before one end-of-loop batch reply. With
        the gate off, the pre-round-15 accumulate-then-reply shape is
        kept byte-identically; oversized results always fall back to the
        individual shm-reply path.

        ``t_arr`` is the pump's arrival stamp for this chunk: the serve
        span starts there (slow-path semantics), so the analyzer can
        carve executor queue wait (arrival → exec start) into its own
        ``exec-queue`` phase instead of leaving it inside reply-ack."""
        if self._reply_batching:
            # Small results collect in a local sink handed to the window
            # every few completions (or ~1ms, whichever first): one
            # window lock + at most one frame per micro-batch instead of
            # per result, without parking a slow task's result behind
            # the whole drain. Dedup bookkeeping batches the same way —
            # the chunk's corr ids were claimed in ONE pass at dispatch
            # (_ring_claim_chunk), completions record in one pass here
            # (_apush_done_many): per-task lock traffic was a measured
            # slice of the 1M-noop drain profile. The flight-off body is
            # flattened inline — the _ring_execute_task →
            # _ring_reply_result → _ring_reply_packaged chain showed up
            # as pure call overhead in the drain-thread profile at 100k
            # noops; the full helper keeps serving the instrumented and
            # edge paths.
            sink: List[tuple] = []
            dones: List[tuple] = []
            sink_t0 = 0.0
            while True:
                try:
                    fn, h, frames = dq.popleft()
                except IndexError:
                    if dones:
                        self._apush_done_many(dones)
                    if sink:
                        self._reply_window(rconn).add_many(sink)
                    return
                if flight.ENABLED:
                    self._ring_execute_task(fn, h, frames, rconn,
                                            sink=sink, dones=dones,
                                            claimed=True, t_arr=t_arr)
                else:
                    t0 = time.time()
                    ok, result = self._ring_execute_one(fn, h, frames)
                    try:
                        rets, out_frames, big = self._package_result_parts(
                            h, ok, result
                        )
                    except Exception as e:
                        logger.exception("ring task reply failed")
                        self._apush_fail(h.get("corr"), e)
                        rconn.send_reply(
                            {"i": h["i"], "r": 1,
                             "e": f"reply packaging failed: {e!r}"}, [],
                        )
                        self._ring_finish_task(h, ok, t0)
                        continue
                    if big:
                        self._ring_reply_packaged(h, rets, out_frames,
                                                  big, rconn)
                    else:
                        corr = h.get("corr")
                        if corr:
                            dones.append((corr, {"rets": rets},
                                          out_frames))
                        sink.append(({"i": h["i"], "rets": rets},
                                     out_frames, None))
                    self._ring_finish_task(h, ok, t0)
                if sink:
                    now = time.monotonic()
                    if sink_t0 == 0.0:
                        sink_t0 = now
                    if len(sink) >= 32 or (now - sink_t0) >= 0.001:
                        if dones:
                            self._apush_done_many(dones)
                            dones = []
                        self._reply_window(rconn).add_many(sink)
                        sink = []
                        sink_t0 = 0.0
        subs = []
        counts = []
        out: List[bytes] = []
        while True:
            try:
                fn, h, frames = dq.popleft()
            except IndexError:
                break
            corr = h.get("corr")
            if corr:
                # Mixed-gate safety: a pusher that arms per-task corr ids
                # must never double-execute here even with windows off.
                state, obj = self._apush_begin(corr)
                if state != "mine":
                    if state == "replay":
                        extras, fr = obj
                        subs.append({"i": h["i"], **dict(extras)})
                        counts.append(len(fr))
                        out.extend(fr)
                    elif state == "wait":
                        self._attach_dup_reply(obj, h["i"], rconn)
                    continue
            t0 = time.time()
            fl = flight.ENABLED
            if fl:
                tm0 = time.monotonic()
            ok, result = self._ring_execute_one(fn, h, frames)
            if fl:
                tm1 = time.monotonic()
                taskpath.record_phase(
                    "exec", h["tid"], tm0, tm1,
                    fn=h.get("name") or h.get("fkey", "")[:10],
                    outcome="ok" if ok else "error", phase="exec",
                )
            try:
                rets, out_frames, big = self._package_result_parts(
                    h, ok, result
                )
            except Exception as e:
                logger.exception("ring chunk reply packaging failed")
                self._apush_fail(h.get("corr"), e)
                subs.append(
                    {"i": h["i"], "e": f"reply packaging failed: {e!r}"}
                )
                counts.append(0)
                self._ring_finish_task(h, ok, t0)
                continue
            if big:
                # shm + head registration: individual async reply path,
                # reusing THIS packaging pass (a second one would register
                # nested-ref borrows twice and re-serialize the value)
                self._ring_reply_packaged(h, rets, out_frames, big, rconn)
            else:
                self._apush_done(h.get("corr"), {"rets": rets}, out_frames)
                subs.append({"i": h["i"], "rets": rets})
                counts.append(len(out_frames))
                out.extend(out_frames)
            if fl:
                now = time.monotonic()
                taskpath.record_phase(
                    "result", h["tid"], tm1, now,
                    fn=h.get("name") or h.get("fkey", "")[:10],
                    phase="result-push",
                )
                flight.record("task.serve", h["tid"], "task",
                              t_arr if t_arr is not None else tm0, now)
            self._ring_finish_task(h, ok, t0)
        if subs:
            rconn.send_reply_batch(subs, counts, out)

    def _ring_execute_task(self, fn, h, frames, rconn, sink=None,
                           dones=None, claimed=False, t_arr=None):
        if not claimed:
            corr = h.get("corr")
            if corr:
                # Plain tasks carry corr (= task id) when reply batching
                # arms deadline re-arm on the pusher: a re-delivered
                # duplicate (dropped window frame, deadline race) replays
                # the recorded outcome or attaches to the in-flight twin
                # — never runs the function a second time. Chunked
                # deliveries claim their corr ids in one pass at dispatch
                # (_ring_claim_chunk) and arrive here claimed.
                state, obj = self._apush_begin(corr)
                if state != "mine":
                    self._ring_reply_dup(state, obj, h, rconn)
                    return
        t0 = time.time()
        fl = flight.ENABLED
        if fl:
            tm0 = time.monotonic()
        ok, result = self._ring_execute_one(fn, h, frames)
        if fl:
            tm1 = time.monotonic()
            taskpath.record_phase(
                "exec", h["tid"], tm0, tm1,
                fn=h.get("name") or h.get("fkey", "")[:10],
                outcome="ok" if ok else "error", phase="exec",
            )
        self._ring_reply_result(h, ok, result, rconn, sink=sink,
                                dones=dones)
        if fl:
            now = time.monotonic()
            taskpath.record_phase(
                "result", h["tid"], tm1, now,
                fn=h.get("name") or h.get("fkey", "")[:10],
                phase="result-push",
            )
            flight.record("task.serve", h["tid"], "task",
                          t_arr if t_arr is not None else tm0, now)
        self._ring_finish_task(h, ok, t0)

    def _ring_reply_dup(self, state, obj, h, rconn):
        """Answer a duplicate ring delivery (dedup said not-"mine"):
        replay the recorded outcome, or attach to the in-flight twin."""
        if state == "replay":
            extras, fr = obj
            rconn.send_reply({"i": h["i"], "r": 1, **dict(extras)},
                             list(fr))
        elif state == "wait":
            self._attach_dup_reply(obj, h["i"], rconn)

    def _ring_reply_result(self, h, ok, result, rconn, sink=None,
                           dones=None):
        """Package + send an execution result from an executor thread
        (shared by the task and actor ring fast paths)."""
        try:
            rets, out_frames, big = self._package_result_parts(h, ok, result)
        except Exception as e:
            logger.exception("ring task reply failed")
            self._apush_fail(h.get("corr"), e)
            rconn.send_reply(
                {"i": h["i"], "r": 1, "e": f"reply packaging failed: {e!r}"},
                [],
            )
            return
        self._ring_reply_packaged(h, rets, out_frames, big, rconn, sink=sink,
                                  dones=dones)

    def _ring_reply_packaged(self, h, rets, out_frames, big, rconn,
                             sink=None, dones=None):
        """Send an ALREADY-packaged result (from an executor thread).
        Packaging must happen exactly once per execution — it registers
        nested-ref borrows, and a second pass would leak them."""
        try:
            if big:
                # Oversized values: write shm here (sync), but the head
                # registration is an RPC — finish on the loop, and only
                # reply once registered (the owner resolves meta via head).
                tid = TaskID.from_hex(h["tid"])
                regs = []
                for i, sobj, ret in big:
                    oid = ObjectID.for_return(tid, i).hex()
                    meta = self._with_xfer(
                        self.shm.put_frames(oid, sobj.to_frames(copy=False))
                    )
                    rets[i] = {**ret, "kind": "shm", "meta": meta}
                    regs.append((oid, meta))

                async def finish():
                    # Any failure must still produce a reply — a silent
                    # drop leaves the submitter's future hanging forever.
                    try:
                        for oid, meta in regs:
                            await self.gcs.call(
                                "object_register", {"oid": oid, "meta": meta}
                            )
                    except Exception as e:
                        self._apush_fail(h.get("corr"), e)
                        rconn.send_reply(
                            {"i": h["i"], "r": 1,
                             "e": f"result registration failed: {e!r}"},
                            [],
                        )
                        return
                    # Cache before send: the shm metas replay cheaply.
                    self._apush_done(h.get("corr"), {"rets": rets},
                                     out_frames)
                    rconn.send_reply(
                        {"i": h["i"], "r": 1, "rets": rets}, out_frames
                    )

                asyncio.run_coroutine_threadsafe(finish(), self.loop)
            else:
                corr = h.get("corr")
                if dones is not None and corr:
                    # Drain-loop micro-batch: the dedup record rides the
                    # sink flush (_apush_done_many, one lock) and is
                    # written before the window frame leaves.
                    dones.append((corr, {"rets": rets}, out_frames))
                else:
                    self._apush_done(corr, {"rets": rets}, out_frames)
                if self._reply_batching:
                    # Small result: coalesce into the connection's reply
                    # window — first result of an idle window flushes
                    # immediately, the rest ride the in-flight frame's
                    # ack (O(bursts) reply messages, and a chunk-mate
                    # never queues behind a sibling's ack). A drain loop
                    # passes a sink so many results share one window
                    # hand-off (add_many).
                    item = ({"i": h["i"], "rets": rets}, out_frames,
                            self._window_tag(h))
                    if sink is not None:
                        sink.append(item)
                    else:
                        self._reply_window(rconn).add(*item)
                else:
                    rconn.send_reply(
                        {"i": h["i"], "r": 1, "rets": rets}, out_frames
                    )
        except Exception as e:
            logger.exception("ring task reply failed")
            self._apush_fail(h.get("corr"), e)
            rconn.send_reply(
                {"i": h["i"], "r": 1, "e": f"reply packaging failed: {e!r}"},
                [],
            )

    # ------------------------------------------------ reply-plane batching

    def _attach_dup_reply(self, fut, rid, rconn):
        """A duplicate delivery raced a still-running execution (the
        pusher's deadline re-arm cancelled its earlier attempt, so the
        in-flight twin's own reply will land on a dead correlation id —
        THIS duplicate is the live one): answer its id the moment the
        execution finishes. Long-running tasks therefore deliver at
        completion, not one re-arm period later."""

        def _done(f, rid=rid, rconn=rconn):
            try:
                extras, fr = f.result()
            except BaseException as e:
                try:
                    rconn.send_reply(
                        {"i": rid, "r": 1,
                         "e": f"TaskError: delivery failed: {e!r}"}, [],
                    )
                except Exception as e2:
                    logger.debug("duplicate-attach error reply lost: %s", e2)
                return
            try:
                rconn.send_reply({"i": rid, "r": 1, **dict(extras)},
                                 list(fr))
            except Exception as e2:
                logger.debug("duplicate-attach reply lost: %s", e2)

        fut.add_done_callback(_done)

    def _reply_window(self, conn):
        """The connection's ReplyWindow, created on first use. One window
        per peer connection (ring or TCP): every execution path feeding
        results back over ``conn`` shares it, so coalescing crosses
        chunk/run boundaries. Ring windows run timer-clocked (gap-paced
        flushes, deferred tail flush on this worker's loop — no mrack
        traffic to contend with the pusher on the ring send lock); TCP
        windows keep the ack clock."""
        w = getattr(conn, "_rt_reply_window", None)
        if w is None:
            from ray_tpu._private.config import rt_config
            from ray_tpu._private.ringconn import RingConnection

            is_ring = isinstance(conn, RingConnection)

            def _defer(delay, cb):
                loop = self.loop
                try:
                    if asyncio.get_running_loop() is loop:
                        loop.call_later(delay, cb)  # on-loop: heap push
                        return
                except RuntimeError:
                    pass
                try:
                    loop.call_soon_threadsafe(loop.call_later, delay, cb)
                except RuntimeError:  # loop closed: flush inline
                    cb()

            w = specframe.ReplyWindow(
                lambda items, _c=conn, _a=not is_ring: (
                    self._reply_window_send(_c, items, ack=_a)
                ),
                max_items=int(rt_config.reply_window_max),
                max_bytes=int(rt_config.reply_window_bytes),
                horizon_s=float(rt_config.reply_window_horizon_s),
                gap_s=(float(rt_config.reply_window_gap_s)
                       if is_ring else None),
                defer=_defer if is_ring else None,
            )
            conn._rt_reply_window = w
            # Keep for the shutdown flush; prune dead connections so
            # churn stays bounded (same discipline as _served_rings).
            self._reply_windows = [
                c for c in self._reply_windows
                if not getattr(c, "_closed", True)
            ] + [conn]
        return w

    def _window_tag(self, h):
        """Per-result taskpath annotation carried through the window (the
        dwell becomes the task's ``reply-window`` phase). None when the
        recorder is off — the hot path then carries no tuple at all."""
        if not flight.ENABLED:
            return None
        return (h.get("tid"), time.monotonic(),
                h.get("name") or h.get("method")
                or h.get("fkey", "")[:10])

    def _reply_window_send(self, conn, items, ack=True):
        """Flush one coalesced multi-result frame: [(sub, frames, tag)]
        -> a single ``bh`` reply message, with the ``wa`` ack request
        that clocks ack-mode (TCP) windows; timer-mode (ring) flushes
        carry no ack request. Transport loss is the peer's problem to
        notice (its per-task deadlines re-arm and the corr-deduped
        re-push replays) — exactly like any other dropped reply."""
        fl = flight.ENABLED
        if fl:
            t0 = time.monotonic()
        counts, flat = protocol.pack_multi_frames(
            [list(f) for _s, f, _t in items]
        )
        subs = [s for s, _f, _t in items]
        nbytes = sum(len(f) for f in flat)
        if faultpoints.ACTIVE:
            try:
                act = faultpoints.fire(
                    "worker.reply.window", err=protocol.ConnectionLost
                )
            except protocol.ConnectionLost as e:
                logger.debug("injected reply-window loss: %s", e)
                act = "drop"
            if act == "drop":
                # The whole frame is lost in transit: every rider's push
                # deadline fires at the driver and the corr-tagged
                # re-push replays the recorded outcomes.
                if fl:
                    flight.record("worker.reply.window", None, "worker",
                                  t0, time.monotonic(), nbytes,
                                  f"drop:batch{len(subs)}")
                return
        try:
            conn.send_reply_batch(subs, counts, flat,
                                  extras={"wa": 1} if ack else None)
        except (protocol.ConnectionLost, OSError) as e:
            logger.debug("reply window flush dropped, peer gone: %s", e)
        self._stats["reply_windows_flushed"] += 1
        self._stats["reply_results_coalesced"] += len(subs)
        if fl:
            now = time.monotonic()
            flight.record("worker.reply.window", None, "worker", t0, now,
                          nbytes, f"ok:batch{len(subs)}")
            for _sub, _fr, tag in items:
                # Sub-threshold dwell (the ring hot path's normal case —
                # results leave with their micro-batch) is delivery
                # noise, not parking: skipping the span keeps the +1
                # record_phase/task tax off the drain loop (a measured
                # ~12us/record at 1M noops) and the unrecorded sliver
                # lands in derived reply-ack, never vanishes. Genuinely
                # parked results (ack-clocked TCP windows, stragglers)
                # still get their truthful reply-window phase.
                if tag is not None and now - tag[1] >= _WINDOW_DWELL_MIN_S:
                    taskpath.record_phase(
                        "reply_window", tag[0], tag[1], now, fn=tag[2],
                        phase="reply-window",
                    )

    def _flush_reply_windows(self):
        """Drain every open reply window (shutdown / graceful node
        drain): buffered results must not die with the process — the
        PR 7 tail-event flush discipline, applied to the reply plane."""
        for conn in self._reply_windows:
            w = getattr(conn, "_rt_reply_window", None)
            if w is None:
                continue
            try:
                w.flush()
            except Exception as e:
                logger.debug("reply-window flush at shutdown failed: %s", e)

    async def rpc_mrack(self, h, frames, conn):
        """Reply-window ack (oneway): the peer's pump settled our last
        coalesced frame — flush whatever completed behind it."""
        w = getattr(conn, "_rt_reply_window", None)
        if w is not None:
            w.on_ack()
        return {}, []

    def _ring_actor_fast_dispatch(self, h, frames, rconn) -> bool:
        """Pump-thread fast path for actor calls: a plain (non-async) method
        with ref-free args on a serial actor, arriving as the caller's next
        in-order sequence, is queued straight onto the actor's executor —
        FIFO pool order IS the admission order, so the seq cursor can
        advance immediately and the event loop never sees the call.
        Anything else (out-of-order arrival, refs, async methods,
        max_concurrency > 1) routes to the slow path, whose semantics are
        authoritative."""
        inst = self.hosted_actors.get(h.get("aid"))
        if not self._actor_fast_inst_ok(inst):
            return False
        if not self._actor_fast_header_ok(h):
            return False
        method = getattr(inst.instance, h.get("method", ""), None)
        if method is None:
            return False
        is_coro = asyncio.iscoroutinefunction(method)
        if self._memory_monitor.is_pressing():
            return False
        caller, seq = h.get("caller", ""), h.get("seq", 0)
        with inst.seq_lock:
            if seq > 0 and seq != inst.next_seq.setdefault(caller, 1):
                return False  # not next (or a retry duplicate): slow path
            if is_coro:
                # Coroutine methods: schedule straight onto the dedicated
                # async-actor loop from the pump thread — the core event
                # loop never sees the call. FIFO scheduling preserves
                # per-caller order; the async-side semaphore bounds
                # concurrency identically to the slow path.
                try:
                    asyncio.run_coroutine_threadsafe(
                        self._ring_run_async_actor_task(
                            inst, method, h, frames, rconn
                        ),
                        self._get_async_loop(),
                    )
                except RuntimeError:
                    return False  # loop shut down
            else:
                try:
                    inst.pool.submit(
                        self._ring_execute_actor_task, inst, method, h,
                        frames, rconn,
                    )
                except RuntimeError:
                    return False  # pool shut down (actor being killed)
            # Queued in order: admit the caller's next call right away.
            if seq > 0:
                inst.next_seq[caller] = seq + 1
                ev = inst.buffered.get(caller, {}).pop(seq + 1, None)
            else:
                ev = None
        if ev is not None:
            try:
                self.loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass
        return True

    def _ring_execute_actor_task(self, inst, method, h, frames, rconn):
        corr = h.get("corr")
        state, obj = self._apush_begin(corr)
        if state != "mine":
            # A duplicate delivery raced past the seq gate: replay the
            # finished outcome; an in-flight twin ("wait") will reply
            # itself — never execute the method a second time.
            if state == "replay":
                extras, fr = obj
                rconn.send_reply({"i": h["i"], "r": 1, **dict(extras)},
                                 list(fr))
            return
        t0 = time.time()
        fl = flight.ENABLED
        if fl:
            tm0 = time.monotonic()
        res = self._exec_actor_call(inst, method, h, frames)
        if fl:
            taskpath.record_phase(
                "exec", h["tid"], tm0, time.monotonic(), fn=h["method"],
                phase="exec",
            )
        if res == "exited":
            # exit_actor(): mirror the slow path's clean-exit protocol.
            self._apush_fail(
                corr, protocol.RpcError("ActorMissing: actor exited")
            )
            rconn.send_reply(
                {"i": h["i"], "r": 1, "e": "ActorMissing: actor exited"},
                [],
            )
            return
        ok, result = res
        self._ring_reply_result(h, ok, result, rconn)
        inst.num_executed += 1
        self._record_task_event({
            "task_id": h["tid"], "name": h["method"], "type": "ACTOR_TASK",
            "actor_id": h["aid"], "corr": h.get("corr"),
            "state": "FINISHED" if ok else "FAILED",
            "start_time": t0, "end_time": time.time(),
            "node_id": self.node_id,
        })

    async def _ring_run_async_actor_task(self, inst, method, h, frames,
                                         rconn):
        """Coroutine twin of _ring_execute_actor_task: runs ON the dedicated
        async-actor loop, gated by the async-side semaphore (shared with the
        slow path's coroutine branch)."""
        corr = h.get("corr")
        state, obj = self._apush_begin(corr)
        if state != "mine":
            if state == "replay":
                extras, fr = obj
                rconn.send_reply({"i": h["i"], "r": 1, **dict(extras)},
                                 list(fr))
            return
        t0 = time.time()
        fl = flight.ENABLED
        if fl:
            tm0 = time.monotonic()
        try:
            async with inst.async_sem:
                arg_slots, plain, kwargs = self.ctx.deserialize_frames(
                    frames
                )
                args = [plain[i] for _k, i in arg_slots]
                _async_actor_id.set(h["aid"])
                _async_task_id.set(h["tid"])
                try:
                    ok, result = True, await method(*args, **kwargs)
                except SystemExit:
                    self.hosted_actors.pop(h["aid"], None)
                    inst.exiting = True
                    self.gcs.notify(
                        "actor_exited",
                        {"actor_id": h["aid"], "clean": True,
                         "reason": "exit_actor"},
                    )
                    self._apush_fail(
                        corr,
                        protocol.RpcError("ActorMissing: actor exited"),
                    )
                    rconn.send_reply(
                        {"i": h["i"], "r": 1,
                         "e": "ActorMissing: actor exited"},
                        [],
                    )
                    return
                except Exception as e:
                    ok, result = False, (e, traceback.format_exc())
        except Exception as e:
            ok, result = False, (e, traceback.format_exc())
        if fl:
            taskpath.record_phase(
                "exec", h["tid"], tm0, time.monotonic(), fn=h["method"],
                phase="exec",
            )
        self._ring_reply_result(h, ok, result, rconn)
        inst.num_executed += 1
        self._record_task_event({
            "task_id": h["tid"], "name": h["method"], "type": "ACTOR_TASK",
            "actor_id": h["aid"], "corr": h.get("corr"),
            "state": "FINISHED" if ok else "FAILED",
            "start_time": t0, "end_time": time.time(),
            "node_id": self.node_id,
        })

    # ------------------------------------------------------- function export

    def export_function(self, fn) -> str:
        key = getattr(fn, "__rt_fn_key__", None)
        if key is not None and key in self.exported_fns:
            return key
        blob = cloudpickle.dumps(fn)
        key = hashlib.sha1(blob).hexdigest()
        if key not in self.exported_fns:
            self.run_sync(
                self.gcs.call("kv_put", {"ns": FN_NS, "key": key}, [blob])
            )
            self.exported_fns.add(key)
        # Keep the blob for push-through: the first push_task carrying this
        # fkey to each peer piggybacks it, so fresh workers skip kv_get.
        self._fn_push.store(key, blob)
        try:
            fn.__rt_fn_key__ = key
        except (AttributeError, TypeError):
            pass
        self.fn_cache[key] = fn
        return key

    def _install_function(self, key: str, fn, blob: Optional[bytes]):
        """A function became known here (kv fetch or piggybacked blob):
        cache it, and arm this worker to push it through on ITS nested
        submissions without re-exporting (the blob is already in the head
        KV — the original exporter put it there)."""
        self.fn_cache[key] = fn
        if blob is not None:
            self._fn_push.store(key, blob)
        self.exported_fns.add(key)
        try:
            fn.__rt_fn_key__ = key
        except (AttributeError, TypeError):
            pass

    async def _load_function(self, key: str):
        fn = self.fn_cache.get(key)
        if fn is not None:
            return fn
        # Miss coalescing: a burst of fresh tasks/actors of K distinct
        # functions issues ONE kv_get_batch, not one kv_get per slot —
        # concurrent misses for the same key share one future, distinct
        # keys queued in the same window ride one batched verb.
        fut = self._fn_loading.get(key)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            # An abandoned waiter (cancelled task) must not surface a
            # never-retrieved warning for the shared future.
            fut.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None
            )
            self._fn_loading[key] = fut
            self._fn_fetch_keys.append(key)
            if not self._fn_fetch_scheduled:
                self._fn_fetch_scheduled = True
                asyncio.get_running_loop().call_soon(self._spawn_fn_fetch)
        return await asyncio.shield(fut)

    def _spawn_fn_fetch(self):
        """One batched fetch per miss window (loop callback)."""
        self._fn_fetch_scheduled = False
        keys = [k for k in self._fn_fetch_keys if k in self._fn_loading]
        self._fn_fetch_keys.clear()
        if keys:
            spawn_logged(self.loop, self._fetch_functions(keys),
                         "worker.fetch_functions")

    async def _fetch_functions(self, keys: List[str]):
        try:
            h, fr = await self._head_call(
                "kv_get_batch", {"ns": FN_NS, "keys": keys}
            )
        except Exception as e:
            for k in keys:
                fut = self._fn_loading.pop(k, None)
                if fut is not None and not fut.done():
                    fut.set_exception(
                        exc.RayTpuError(f"function table fetch failed: {e}")
                    )
            return
        try:
            found = list(h.get("found") or ())
            pos = 0
            for k, ok in zip(keys, found):
                blob = fr[pos] if ok and pos < len(fr) else None
                if ok:
                    pos += 1
                fut = self._fn_loading.pop(k, None)
                if fut is None or fut.done():
                    continue
                if blob is None:
                    fut.set_exception(exc.RayTpuError(
                        f"function {k} not found in function table"
                        if not ok else
                        f"function {k} missing from kv_get_batch reply"
                    ))
                    continue
                try:
                    fn = cloudpickle.loads(blob)
                except Exception as e:
                    fut.set_exception(exc.RayTpuError(
                        f"function {k} failed to load: {e!r}"
                    ))
                    continue
                self._install_function(k, fn, blob)
                fut.set_result(fn)
        finally:
            # Malformed/truncated reply (or any parse error above): a
            # leftover future must fail, never hang — it is shared by
            # every coalesced waiter and by all future misses of its key.
            for k in keys:
                fut = self._fn_loading.pop(k, None)
                if fut is not None and not fut.done():
                    fut.set_exception(exc.RayTpuError(
                        f"function {k} missing from kv_get_batch reply"
                    ))

    # -------------------------------------------------------------- ownership

    def _dec_ref_local(self, oid: str):
        rec = self.owned.get(oid)
        if rec is None:
            return
        rec["count"] -= 1
        self._maybe_free(oid)

    def _apply_borrow(self, oid: str, owner: tuple, my_addr: tuple,
                      to_notify: Dict[tuple, List[str]]):
        if owner == my_addr:
            rec = self.owned.get(oid)
            if rec is not None:
                rec["count"] += 1  # a local materialized copy
            return
        b = self.borrowed.get(oid)
        if b is None:
            self.borrowed[oid] = {"count": 1, "owner": owner}
            to_notify.setdefault(owner, []).append(oid)
        else:
            b["count"] += 1

    def _drain_borrows(self):
        """Register queued deserialize-time borrows (one loop callback per
        burst; one grouped add_borrow notify per owner). Entries are either
        (oid_hex, owner_tuple) from the per-ref hook, or (None, [ObjectRef])
        batches from the batched deserialize hook — the batch form defers
        hex/owner-tuple work to HERE, off the deserializing thread."""
        self._borrow_drain_scheduled = False
        q = self._borrow_queue
        to_notify: Dict[tuple, List[str]] = {}
        my_addr = tuple(self.addr or ())
        while q:
            oid, owner = q.popleft()
            if oid is None:
                for ref in owner:  # owner slot carries the ref batch
                    ro = ref.owner_address
                    if not ro:
                        continue
                    self._apply_borrow(
                        ref._id._bytes.hex(), tuple(ro), my_addr, to_notify
                    )
                continue
            self._apply_borrow(oid, owner, my_addr, to_notify)
        for owner, oids in to_notify.items():
            spawn_logged(
                self.loop,
                self._notify_owner_many(owner, "add_borrow", oids),
                "worker.notify_owner.add_borrow",
            )

    def _drain_releases(self):
        """Process every queued ObjectRef release in one loop callback.

        Shm frees are announced to the head as ONE grouped object_free
        notify instead of one per object (reference batches refcount
        traffic the same way: ``core_worker/reference_counter`` flushes
        deltas, not per-ref RPCs). Borrowed (foreign-owned) refs return
        their borrow to the owner when the last local copy dies."""
        self._release_drain_scheduled = False
        # adds queued in the same window must reach the owner first
        self._drain_borrows()
        q = self._release_queue
        freed: List[str] = []
        to_register: List[tuple] = []
        to_release: Dict[tuple, List[str]] = {}
        to_add: Dict[tuple, List[str]] = {}
        my_addr = tuple(self.addr or ())
        while q:
            kind, payload = q.popleft()
            if kind == "reg":
                to_register.append(payload)
                continue
            if kind == "pin":
                for oid, owner in payload:
                    rec = self.owned.get(oid)
                    if rec is not None:
                        rec["borrows"] += 1
                    elif owner and tuple(owner) != my_addr:
                        to_add.setdefault(tuple(owner), []).append(oid)
                continue
            # "dec": payload is the hex, or the ObjectID when the release
            # hook deferred the conversion off the GC'ing thread.
            oid = payload if type(payload) is str else payload._bytes.hex()
            b = self.borrowed.get(oid)
            if b is not None:
                b["count"] -= 1
                if b["count"] <= 0:
                    self.borrowed.pop(oid, None)
                    to_release.setdefault(tuple(b["owner"]), []).append(oid)
                continue
            rec = self.owned.get(oid)
            if rec is None:
                continue
            rec["count"] -= 1
            self._maybe_free(oid, free_sink=freed)
        for owner, oids in to_add.items():
            spawn_logged(
                self.loop,
                self._notify_owner_many(owner, "add_borrow", oids),
                "worker.notify_owner.add_borrow",
            )
        for owner, oids in to_release.items():
            spawn_logged(
                self.loop,
                self._notify_owner_many(owner, "release_borrow", oids),
                "worker.notify_owner.release_borrow",
            )
        # Registrations flush BEFORE frees: a register landing after the
        # free of the same (dying) object would leave the head directory
        # pointing at reclaimed arena memory forever. The reverse race —
        # a reconstruction's re-register popped by the old free in the
        # same batch — only costs a directory miss, which readers already
        # survive via pull-from-owner.
        if to_register:
            try:
                self.gcs.notify("object_register", {"items": to_register})
            except protocol.ConnectionLost as e:
                logger.debug("object_register batch dropped, head gone: %s", e)
        if freed:
            try:
                self.gcs.notify("object_free", {"oids": freed})
            except protocol.ConnectionLost as e:
                logger.debug("object_free batch dropped, head gone: %s", e)

    def _record_lineage(self, tid_hex, header, frames, resources, strategy,
                        nret):
        """Remember a task spec while any of its return refs is alive, so a
        lost output can be recomputed (deterministic ObjectIDs make the
        resubmitted task produce the same ids)."""
        nbytes = sum(len(f) for f in frames) + 512
        if nbytes > self._LINEAGE_MAX_BYTES:
            return  # a single huge-arg task never evicts everyone else
        self._lineage[tid_hex] = {
            "header": header, "frames": frames, "resources": resources,
            "strategy": strategy, "bytes": nbytes, "live": nret,
        }
        self._lineage_bytes += nbytes
        while self._lineage_bytes > self._LINEAGE_MAX_BYTES and self._lineage:
            old, rec = self._lineage.popitem(last=False)
            if old == tid_hex:  # never evict the entry just recorded
                self._lineage[old] = rec
                break
            self._lineage_bytes -= rec["bytes"]

    def _drop_lineage_for(self, oid: str):
        """Last live ref to a return object died → its slot no longer needs
        the producing-task spec."""
        if len(oid) != 56 or int(oid[48:56], 16) & 0x80000000:
            return  # put object (or foreign id): no lineage
        rec = self._lineage.get(oid[:48])
        if rec is None:
            return
        rec["live"] -= 1
        if rec["live"] <= 0:
            self._lineage_bytes -= rec["bytes"]
            self._lineage.pop(oid[:48], None)

    def _maybe_free(self, oid: str, free_sink: Optional[List[str]] = None):
        rec = self.owned.get(oid)
        if rec is None or rec["count"] > 0 or rec["borrows"] > 0:
            return
        self.owned.pop(oid, None)
        self._drop_lineage_for(oid)
        entry = self.memory_store.pop(oid, None)
        self.store_events.pop(oid, None)
        if entry is not None and entry[0] in ("shm", "dev"):
            if entry[0] == "shm":
                self.shm.free(oid, entry[1])
            else:
                # Device plane: dropping the table entry releases the
                # last host-side reference; jax frees the device buffers.
                self._device_objects.pop(oid, None)
            if free_sink is not None:
                free_sink.append(oid)  # caller sends one grouped notify
            else:
                try:
                    self.gcs.notify("object_free", {"oids": [oid]})
                except protocol.ConnectionLost as e:
                    logger.debug("object_free %s dropped, head gone: %s",
                                 oid, e)
        # Refs nested inside this value were pinned for its lifetime.
        if rec.get("nested"):
            self._release_borrows(rec["nested"])

    def _register_owned(self, oid: str, nested: Optional[list] = None):
        self.owned[oid] = {"count": 1, "borrows": 0, "nested": nested or []}

    def _enqueue_ref_op(self, op: tuple):
        """Append a refcount operation to the SINGLE ordered op queue and
        make sure one drain is pending. Pins and decrements MUST share a
        queue: with separate callbacks, a drain scheduled before a pin can
        consume decrements enqueued after it — freeing an object whose pin
        is still in flight (observed as vanishing shuffle pieces)."""
        self._release_queue.append(op)
        if self._release_drain_scheduled:
            return
        self._release_drain_scheduled = True
        try:
            # Short flush window (not next-tick): a sequential put/free
            # loop otherwise drains once per op, sending a 1-item head
            # notify each time. 5ms of latency on ref release is invisible
            # (arena reclaim + head directory tolerate it; remote readers
            # racing a free already handle miss-then-pull), while a burst
            # collapses to one notify + one pubsub fanout.
            self.loop.call_soon_threadsafe(
                lambda: self.loop.call_later(0.005, self._drain_releases)
            )
        except RuntimeError:
            self._release_drain_scheduled = False

    def _add_borrows(self, entries: List[tuple]):
        """entries: [(oid_hex, owner_addr_or_None)]. Local refs increment the
        borrow count; foreign refs notify their owner (reference: borrow
        registration in ``reference_counter.h``). Ordered through the shared
        ref-op queue so the pin always applies before any release enqueued
        after it, regardless of which thread enqueues what."""
        if not entries:
            return  # hot path: no-ref tasks must not pay a loop wakeup
        self._enqueue_ref_op(("pin", list(entries)))

    def _release_borrows(self, entries: List[tuple]):
        # Pending deserialize-time borrow registrations must land at the
        # owner before these container-credit releases do.
        self._drain_borrows()
        my_addr = tuple(self.addr or ())
        to_release: Dict[tuple, List[str]] = {}
        for oid, owner in entries:
            rec = self.owned.get(oid)
            if rec is not None:
                rec["borrows"] -= 1
                self._maybe_free(oid)
            elif owner and tuple(owner) != my_addr:
                to_release.setdefault(tuple(owner), []).append(oid)
        for owner, oids in to_release.items():
            spawn_logged(
                self.loop,
                self._notify_owner_many(owner, "release_borrow", oids),
                "worker.notify_owner.release_borrow",
            )

    async def _notify_owner(self, addr, method: str, oid: str):
        try:
            conn = await self.get_peer(addr)
            conn.notify(method, {"oid": oid})
        except (protocol.ConnectionLost, ConnectionRefusedError,
                OSError) as e:
            logger.debug("%s(%s) to owner %s dropped, owner gone: %s",
                         method, oid, addr, e)

    async def _notify_owner_many(self, addr, method: str, oids: List[str]):
        try:
            conn = await self.get_peer(addr)
            conn.notify(method, {"oids": oids})
        except (protocol.ConnectionLost, ConnectionRefusedError,
                OSError) as e:
            logger.debug("%s(%d oids) to owner %s dropped, owner gone: %s",
                         method, len(oids), addr, e)

    # ------------------------------------------------------------ put / get

    def _next_put_id(self) -> ObjectID:
        tid = getattr(self.current_task_id, "value", None)
        if tid is None:
            tid = TaskID.of()
            self.current_task_id.value = tid
        idx = getattr(self.put_counter, "value", 0) + 1
        self.put_counter.value = idx
        return ObjectID.for_put(tid, idx)

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() does not accept ObjectRef (matches reference)")
        try:
            sobj, nested_refs = collect_refs_during(
                lambda: self.ctx.serialize(value, allow_device=True)
            )
        except serialization.DeviceObjectIntercept as d:
            # Device plane: the payload never reaches cloudpickle — only
            # structured metadata crosses the control plane, and the
            # array stays pinned on device in _device_objects.
            return devstore.put_device(self, d.value)
        oid = self._next_put_id()
        nested = [
            (r.id().hex(), list(r.owner_address or ())) for r in nested_refs
        ]
        size = sobj.total_bytes()
        # Large values go straight into shm inside this call (one memcpy
        # from the raw buffer views — zero-copy is safe because the write
        # happens before put() returns); small inline values keep the
        # default copy since the memory store holds the frames while the
        # caller may mutate the source.
        frames = sobj.to_frames(copy=size <= INLINE_OBJECT_MAX)
        hex_ = oid.hex()
        # Store on the CALLER's thread: the arena create/copy/seal are
        # mutex'd native calls, and a run_sync round-trip costs more in
        # cross-thread handoff than the store itself for small/mid objects.
        # Concurrent readers are safe: a remote pull that races the dict
        # write long-polls store_events (rpc_pull_object -> _wait_local),
        # which the scheduled callback below signals. Ownership/borrow
        # records are created only after the store succeeds — a failed
        # store (e.g. /dev/shm exhausted) must not leak an owned record or
        # borrow pins for a ref that is never returned.
        if size <= INLINE_OBJECT_MAX:
            self._add_borrows(nested)  # pinned until this object is freed
            self._register_owned(hex_, nested=nested)
            self.memory_store[hex_] = ("mem", frames)
            self._signal_store_event(hex_)
        else:
            meta = self._with_xfer(self.shm.put_frames(hex_, frames))
            self._add_borrows(nested)  # pinned until this object is freed
            self._register_owned(hex_, nested=nested)
            self.memory_store[hex_] = ("shm", meta)
            self._signal_store_event(hex_)
            self._register_object_async(hex_, meta)
        return ObjectRef(oid, tuple(self.addr))

    def _register_object_async(self, hex_: str, meta: dict):
        """Queue a head directory registration on the SAME ordered ref-op
        queue the frees ride (a separate buffer/timer could flush a free
        BEFORE its object's registration, resurrecting a freed object as a
        stale directory entry — the split-queue reordering class
        _enqueue_ref_op documents). A put-burst flushes as ONE batched
        notify; a reader racing the 5ms window falls back to
        pull-from-owner (reference analog: owner-resolved locations,
        ownership_object_directory.h)."""
        self._enqueue_ref_op(("reg", (hex_, meta)))

    def _signal_store_event(self, hex_: str):
        """Wake any loop-side waiter (_wait_local) for an object stored from
        a non-loop thread. asyncio.Event is not thread-safe: the set must
        run on the loop."""
        ev = self.store_events.get(hex_)
        if ev is not None:
            try:
                self.loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass

    def put_raw_frames(self, frames: List[Any],
                       transient: bool = False) -> Tuple[str, dict]:
        """Store raw frames (no serialization envelope) in the shm store and
        register the location with the head; returns (oid hex, meta).

        Lifetime is the CALLER's to manage (e.g. the DAG device channels
        free via object_free once consumed) — no ownership record is
        created. ``transient``: consumers copy on read, so frees may fully
        unmap. Callable from any thread."""
        oid = self._next_put_id().hex()
        meta = self._with_xfer(
            self.shm.put_frames(oid, frames, transient=transient)
        )
        self.run_sync(
            self.gcs.call("object_register", {"oid": oid, "meta": meta})
        )
        return oid, meta

    def put_serialized(self, frames: List[bytes], total_bytes: int) -> ObjectRef:
        """Store pre-serialized frames as a new owned object (skips the
        second serialization a put(value) would do). Caller guarantees the
        value holds no nested ObjectRefs (no borrow pinning happens here)."""
        oid = self._next_put_id()
        hex_ = oid.hex()
        self.run_sync(self._store_object(hex_, frames, total_bytes))
        self._register_owned(hex_)
        return ObjectRef(oid, tuple(self.addr))

    async def _store_object(self, hex_: str, frames: List[bytes], size: int):
        if size <= INLINE_OBJECT_MAX:
            self.memory_store[hex_] = ("mem", frames)
        else:
            if size >= 8 * 1024 * 1024:
                # Big payload: copy on an executor thread so the event loop
                # keeps serving RPCs during the multi-ms memcpy (the native
                # arena's create/copy/seal are mutex'd and safe off-loop).
                loop = asyncio.get_running_loop()
                meta = await loop.run_in_executor(
                    None, self.shm.put_frames, hex_, frames
                )
                meta = self._with_xfer(meta)
            else:
                meta = self._with_xfer(self.shm.put_frames(hex_, frames))
            self.memory_store[hex_] = ("shm", meta)
            # Fire-and-forget: we are the OWNER, so any later object_free for
            # this oid leaves on the same head connection and is pipelined
            # behind this registration (in-order per connection). A reader
            # that races the registration misses the directory and falls back
            # to pull-from-owner (_fetch_remote), which we can always serve.
            # This keeps the head RTT out of every put() (reference analog:
            # plasma seals locally; location updates flow async via the
            # owner-resolved directory, ownership_object_directory.h).
            self.gcs.notify("object_register", {"oid": hex_, "meta": meta})
        ev = self.store_events.get(hex_)
        if ev is not None:
            ev.set()

    def _store_error(self, hex_: str, err: Exception):
        self.memory_store[hex_] = ("err", err)
        ev = self.store_events.get(hex_)
        if ev is not None:
            ev.set()

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        values = self._try_get_local(refs)
        if values is None:
            values = self.run_sync(self._get_many(refs, timeout))
        return values[0] if single else values

    def _try_get_local(self, refs) -> Optional[list]:
        """Caller-thread fast path: when EVERY ref already resolves in the
        local store, deserialize right here — the loop round-trip
        (run_sync handoff + task + wakeups, ~6 epoll cycles measured) is
        pure overhead for an object that's already in hand. Any miss,
        stale shm meta, or error entry falls back to the authoritative
        async path (waiting, remote fetch, reconstruction). Store reads
        and arena gets are thread-safe; deserialize already runs on
        executor threads elsewhere."""
        # Two phases: resolve EVERY ref's frames first, deserialize after —
        # a miss on the last ref must not have already paid for (and then
        # discarded) the earlier refs' deserialization.
        resolved = []
        for ref in refs:
            entry = self.memory_store.get(ref.id().hex())
            if entry is None:
                return None
            kind = entry[0]
            if kind == "shm":
                frames = self.shm.get_frames(ref.id().hex(), entry[1])
                if frames is None:
                    return None  # spilled/moved: slow path refreshes
                resolved.append(("mem", frames))
            elif kind == "dev":
                # Device plane: the value itself is in the device table
                # (owner put, or a consumer's cached pull) — no frames,
                # no deserialization.
                arr = self._device_objects.get(ref.id().hex())
                if arr is None:
                    return None  # evicted under us: slow path re-pulls
                resolved.append(("devval", arr))
            elif kind in ("mem", "err"):
                resolved.append(entry)
            else:
                return None
        out = []
        for kind, payload in resolved:
            if kind == "devval":
                out.append(payload)
                continue
            try:
                if kind == "err":
                    raise payload
                out.append(self.ctx.deserialize_frames(payload))
            except exc.RayTpuError:
                raise
            except Exception:
                return None  # any decode hiccup: slow path is authoritative
        return out

    async def _get_many(self, refs: List[ObjectRef], timeout: Optional[float]):
        # ONE deadline for the whole call: the batch resolve and the
        # per-ref paths share it, so get(refs, timeout=T) surfaces
        # GetTimeoutError at ~T even when the batch phase consumed time.
        deadline = None if timeout is None else time.monotonic() + timeout
        prefetch = None
        if len(refs) > 1:
            prefetch = await self._batch_resolve(refs, deadline)
        results = await asyncio.gather(
            *(self._get_one(r, timeout, prefetch=prefetch,
                            deadline=deadline) for r in refs)
        )
        out = []
        for v in results:
            if isinstance(v, Exception):
                raise v
            out.append(v)
        return out

    async def _batch_resolve(self, refs, deadline) -> Optional[dict]:
        """Vectorized remote resolution for a multi-ref get: ONE directory
        round-trip for every unknown oid, then ONE pull RPC per distinct
        owner for whatever the directory misses (reference: batched
        location lookups + owner-grouped pulls, Wang et al. NSDI'21 §4).
        Returns {oid_hex: store entry} for what it resolved; refs left out
        fall back to the authoritative per-ref path, so errors/timeouts
        keep their exact single-ref semantics. Never raises."""
        my_addr = tuple(self.addr or ())
        unknown: Dict[str, tuple] = {}
        for ref in refs:
            hex_ = ref._id._bytes.hex()
            if hex_ in unknown or hex_ in self.memory_store:
                continue
            owner = tuple(ref.owner_address or ())
            if owner == my_addr:
                continue  # owned-but-pending: _wait_local handles it
            unknown[hex_] = owner
        if not unknown:
            return None
        resolved: Dict[str, tuple] = {}
        oids = list(unknown)
        try:
            tmo = None
            retries = None
            if deadline is not None:
                # The whole retry envelope must fit the caller's budget:
                # one attempt spanning the remaining time, no re-issues
                # (the per-ref fallback is the retry path here).
                tmo = max(deadline - time.monotonic(), 0.001)
                retries = 0
            h, _ = await self._head_call(
                "object_lookup_batch", {"oids": oids}, timeout=tmo,
                retries=retries,
            )
            for oid, meta in zip(oids, h.get("metas") or []):
                if meta is not None:
                    resolved[oid] = ("shm", meta)
        except (asyncio.TimeoutError, protocol.RpcError,
                protocol.ConnectionLost, OSError) as e:
            # Per-ref path retries the directory with full semantics.
            logger.debug("batched directory lookup (%d oids) failed, "
                         "falling back to per-ref: %s", len(oids), e)
        by_owner: Dict[tuple, List[str]] = {}
        for oid, owner in unknown.items():
            if oid not in resolved and owner:
                by_owner.setdefault(owner, []).append(oid)
        if by_owner:
            await asyncio.gather(*(
                self._pull_batch_from_owner(owner, oids_, deadline, resolved)
                for owner, oids_ in by_owner.items()
            ))
        return resolved

    async def _pull_batch_from_owner(self, owner, oids: List[str], deadline,
                                     resolved: Dict[str, tuple]):
        """Pull a whole owner's batch over a single RPC with multi-object
        frames. Failures leave the oids unresolved (the per-ref pull
        reproduces the exact error/timeout behavior). The attempt is
        always deadline-bounded: a dropped batch reply must hand over to
        the per-ref path, not pin the whole get() forever."""
        from ray_tpu._private.config import rt_config

        fl = flight.ENABLED
        if fl:
            fl_t0 = time.monotonic()
            fl_fid = flight.next_id()
        try:
            if faultpoints.ACTIVE:
                if await faultpoints.async_fire("worker.pull") == "drop":
                    return  # reply lost; per-ref path takes over
            conn = await self.get_peer(owner)
            extras = {"oids": oids}
            if fl:
                extras["fid"] = fl_fid
            call = conn.call("pull_object_batch", extras)
            tmo = float(rt_config.rpc_deadline_s)
            if deadline is not None:
                tmo = min(tmo, max(deadline - time.monotonic(), 0))
            hh, frames = await asyncio.wait_for(call, tmo)
        except (asyncio.TimeoutError, protocol.RpcError,
                protocol.ConnectionLost, ConnectionRefusedError,
                OSError) as e:
            if fl:
                flight.record("worker.pull_batch", fl_fid, "worker", fl_t0,
                              time.monotonic(), 0,
                              f"error:{type(e).__name__}")
            return
        if fl:
            flight.record("worker.pull_batch", fl_fid, "worker", fl_t0,
                          time.monotonic(), sum(len(f) for f in frames),
                          "ok")
        res = hh.get("res") or []
        per_obj = protocol.unpack_multi_frames(
            [r.get("n", 0) for r in res], frames
        )
        for oid, r, fl in zip(oids, res, per_obj):
            kind = r.get("kind")
            if kind == "shm":
                resolved[oid] = ("shm", r["meta"])
            elif kind == "dev":
                resolved[oid] = ("dev", r["spec"])
            elif kind == "mem":
                resolved[oid] = ("mem", fl)
            elif kind == "err":
                resolved[oid] = ("err", _loads_maybe(fl))

    async def _get_one(self, ref: ObjectRef, timeout: Optional[float] = None,
                       prefetch: Optional[dict] = None, deadline=None):
        value = await self._get_one_attempt(ref, timeout, prefetch=prefetch,
                                            deadline=deadline)
        if isinstance(value, exc.ObjectLostError):
            initiated = self._try_reconstruct(ref)
            if initiated:
                tid_hex = ref.id().hex()[:48]
                try:
                    value = await self._get_one_attempt(ref, timeout)
                finally:
                    # Only the getter that STARTED the resubmission clears
                    # the in-flight guard; a waiter clearing it early would
                    # let a third getter double-submit the task.
                    if initiated == 2:
                        self._reconstructing.discard(tid_hex)
        return value

    def _try_reconstruct(self, ref: ObjectRef) -> int:
        """Resubmit the task that produced a lost owned object (reference:
        ``object_recovery_manager.h:41`` — recovery via deterministic object
        ids + lineage resubmit). Returns 0 when reconstruction is
        impossible, 1 when a resubmission by another getter is in flight
        (wait for it), 2 when THIS call started one (caller owns the
        guard)."""
        hex_ = ref.id().hex()
        if tuple(ref.owner_address or ()) != tuple(self.addr or ()):
            return 0  # only the owner reconstructs
        if len(hex_) != 56 or int(hex_[48:56], 16) & 0x80000000:
            return 0  # puts have no producing task
        tid_hex = hex_[:48]
        rec = self._lineage.get(tid_hex)
        if rec is None:
            return 0
        if tid_hex in self._reconstructing:
            return 1  # another get already resubmitted; just wait
        self._reconstructing.add(tid_hex)
        logger.warning(
            "object %s lost; reconstructing by resubmitting its task",
            hex_[:12],
        )
        tid = TaskID.from_hex(tid_hex)
        nret = rec["header"].get("nret", 1)
        for i in range(max(nret, 1)):
            o = ObjectID.for_return(tid, i).hex()
            self.memory_store.pop(o, None)
            ev = self.store_events.get(o)
            if ev is not None:
                ev.clear()
        # Borrows were already released when the first execution replied; a
        # second release would corrupt the counts.
        header = dict(rec["header"], borrows=[])
        self._enqueue_dispatch(
            self._dispatch_task_fast,
            (header, rec["frames"], rec["resources"], rec["strategy"], 2),
        )
        return 2

    async def _get_one_attempt(
        self, ref: ObjectRef, timeout: Optional[float] = None,
        prefetch: Optional[dict] = None, deadline=None,
    ):
        hex_ = ref.id().hex()
        if deadline is None:
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
        entry = self.memory_store.get(hex_)
        if entry is None and tuple(ref.owner_address or ()) == tuple(self.addr):
            # We own it but it is not ready yet: wait for local completion.
            entry = await self._wait_local(hex_, deadline)
        if entry is None and prefetch is not None:
            # Resolved by the batched directory lookup / owner-coalesced
            # pull (_batch_resolve); a miss falls through to the per-ref
            # path, which is authoritative.
            entry = prefetch.get(hex_)
        if entry is None:
            entry = await self._fetch_remote(ref, deadline)
        kind = entry[0]
        if kind == "shm" and devstore.is_device_meta(entry[1]):
            # Directory hit for a device-plane object: the meta carries
            # layout + owner, never bytes — route to the device pull.
            kind = "dev"
        if kind == "dev":
            try:
                return await devstore.materialize(
                    self, hex_, entry[1], ref, deadline
                )
            except exc.RayTpuError as e:
                return e
        if kind == "err":
            return entry[1]
        if kind == "mem":
            return self.ctx.deserialize_frames(entry[1])
        if kind == "shm":
            frames = await self._frames_for_meta(hex_, entry[1])
            if frames is None:
                # Our meta may be stale — e.g. another process spilled the
                # object to disk under memory pressure. The head's directory
                # entry is authoritative; refresh and retry locally.
                try:
                    hh, _ = await self._head_call(
                        "object_lookup", {"oid": hex_}
                    )
                except (protocol.RpcError, protocol.ConnectionLost, OSError):
                    hh = {}
                if hh.get("found") and hh["meta"] != entry[1]:
                    entry = ("shm", hh["meta"])
                    self.memory_store[hex_] = entry
                    frames = await self._frames_for_meta(hex_, hh["meta"])
            if frames is None:
                # Not mappable here: bulk-fetch through the native transfer
                # plane into a local segment (C++ end to end).
                frames = await self._native_fetch(hex_, entry[1], deadline)
            if frames is None:
                # Native plane unavailable (or object lost): fall back to
                # pulling the bytes over RPC — from the worker that spilled
                # the object (its meta carries that addr) or the owner.
                meta = entry[1] if isinstance(entry[1], dict) else {}
                spill_addr = meta.get("addr") if "spill" in meta else None
                if spill_addr and tuple(spill_addr) == tuple(self.addr or ()):
                    spill_addr = None  # we ARE the spiller; file is gone
                try:
                    entry = await self._pull_from_owner(
                        ref, deadline, inline=True,
                        addr=tuple(spill_addr) if spill_addr else None,
                    )
                except exc.RayTpuError as e:
                    return e
                if entry[0] == "err":
                    return entry[1]
                if entry[0] == "mem":
                    # Cache: repeated gets must not re-transfer the payload.
                    self.memory_store[hex_] = entry
                    return self.ctx.deserialize_frames(entry[1])
                return exc.ObjectLostError(hex_, "shm segment missing")
            return self.ctx.deserialize_frames(frames)
        return exc.ObjectLostError(hex_, f"bad store entry {kind}")

    async def _frames_for_meta(self, hex_: str, meta):
        """Loop-side frame resolution for one shm/spill meta. Spilled
        copies restore on the spill IO pool — a disk/bucket read must not
        block the event loop (reference: AsyncRestoreSpilledObject runs on
        IO workers); arena reads are sub-ms native calls and stay sync."""
        if isinstance(meta, dict) and "spill" in meta:
            raw = await self.shm.spill.read_async(meta, self.loop)
            return [memoryview(f) for f in raw] if raw is not None else None
        return self.shm.get_frames(hex_, meta)

    def _with_xfer(self, meta: dict) -> dict:
        """Stamp shm metadata with this worker's transfer-server address so
        any process that cannot map the segment can bulk-fetch it natively.

        When the memtrack plane is on, also stamp the storing node and
        owner address — every registration path funnels through here, so
        the head directory can attribute each entry to a node (for the
        per-node store gauges/reconciliation) and to an owner (for leak
        detection when that owner dies)."""
        if meta is not None and self.xfer_addr is not None:
            meta = dict(meta, xfer=list(self.xfer_addr))
        if memtrack.ENABLED and meta is not None:
            meta = dict(meta, node=self.node_id,
                        owner=list(self.addr or ()))
        return meta

    async def _native_fetch(self, hex_: str, meta: dict, deadline=None):
        """Fetch a remote shm object through the C++ transfer plane into a
        local per-object segment; returns zero-copy frames or None. The
        socket IO is bounded by the get() deadline."""
        xfer = meta.get("xfer") if isinstance(meta, dict) else None
        if not xfer:
            return None
        try:
            from ray_tpu.native import xfer as native_xfer
        except Exception:
            return None
        timeout_s = None
        if deadline is not None:
            timeout_s = deadline - time.monotonic()
            if timeout_s <= 0:
                return None
        store = getattr(self.shm, "fallback", self.shm)
        dest = store.seg_name(hex_)
        loop = asyncio.get_running_loop()
        new_meta = await loop.run_in_executor(
            None, native_xfer.fetch_to_segment,
            xfer[0], xfer[1], meta, hex_, dest, timeout_s,
        )
        if new_meta is None:
            return None
        frames = store.get_frames(hex_, new_meta)
        if frames is not None:
            if new_meta.get("size", 0) > 0:
                # We materialized this local copy (size 0 = a complete copy
                # already existed): own its unlink on free/evict.
                store._created[hex_] = True
            # Repeat gets must resolve locally, not re-stream the payload
            # (the arena-meta miss would otherwise re-fetch every time).
            self.memory_store[hex_] = ("shm", dict(new_meta))
        return frames

    async def _wait_local(self, hex_: str, deadline):
        ev = self.store_events.get(hex_)
        if ev is None:
            ev = asyncio.Event()
            self.store_events[hex_] = ev
        entry = self.memory_store.get(hex_)
        if entry is not None:
            return entry
        try:
            if deadline is None:
                await ev.wait()
            else:
                await asyncio.wait_for(ev.wait(), max(deadline - time.monotonic(), 0))
        except asyncio.TimeoutError:
            raise exc.GetTimeoutError(f"get() timed out waiting for {hex_}")
        return self.memory_store.get(hex_)

    async def _fetch_remote(self, ref: ObjectRef, deadline):
        hex_ = ref.id().hex()
        # 1) check the shm directory (any process on this machine can attach)
        h, _ = await self._head_call("object_lookup", {"oid": hex_})
        if h.get("found"):
            return ("shm", h["meta"])
        # 2) pull from the owner
        return await self._pull_from_owner(ref, deadline)

    async def _pull_from_owner(self, ref: ObjectRef, deadline, inline=False,
                               addr=None):
        """Fetch from the owning worker. inline=True forces the owner to send
        the bytes over the wire even for shm-backed objects (used when this
        process cannot map the shared store). ``addr`` overrides the target
        (e.g. the worker that spilled the object holds its disk copy); such
        direct pulls do not long-poll ownership."""
        from ray_tpu._private.config import rt_config

        hex_ = ref.id().hex()
        owner = tuple(addr or ref.owner_address or ())
        if not owner:
            raise exc.ObjectLostError(hex_, "no owner address on ref")
        # Re-armed long-poll: each attempt is bounded by the RPC deadline
        # even when get() has none, so a dropped pull reply re-issues the
        # pull instead of hanging this getter forever; transient connection
        # failures get a few jittered retries before ObjectLostError.
        attempt_s = float(rt_config.rpc_deadline_s)
        conn_failures = 0
        retry = Backoff(base=0.05, cap=1.0)
        pull_extras = {"oid": hex_, "inline": inline,
                       "direct": addr is not None}
        fl = flight.ENABLED
        if fl:
            # One join key for every re-armed attempt of this pull; the
            # owner's server-side span shares it.
            pull_extras["fid"] = flight.next_id()
        while True:
            if fl:
                fl_t0 = time.monotonic()
            try:
                if faultpoints.ACTIVE:
                    if await faultpoints.async_fire("worker.pull") == "drop":
                        # Reply lost in transit: behave exactly like the
                        # attempt-deadline expiring.
                        raise asyncio.TimeoutError()
                conn = await self.get_peer(owner)
                tmo = attempt_s
                if deadline is not None:
                    tmo = min(tmo, max(deadline - time.monotonic(), 0))
                hh, frames = await asyncio.wait_for(
                    conn.call("pull_object", pull_extras),
                    tmo,
                )
                if fl:
                    flight.record("worker.pull", pull_extras.get("fid"),
                                  "worker", fl_t0, time.monotonic(),
                                  sum(len(f) for f in frames), "ok")
                break
            except asyncio.TimeoutError:
                if fl:
                    flight.record("worker.pull", pull_extras.get("fid"),
                                  "worker", fl_t0, time.monotonic(), 0,
                                  "timeout")
                if deadline is not None and time.monotonic() >= deadline:
                    raise exc.GetTimeoutError(
                        f"get() timed out pulling {hex_}"
                    )
                await asyncio.sleep(retry.next_delay())
            except (protocol.ConnectionLost, ConnectionRefusedError,
                    OSError) as e:
                conn_failures += 1
                if conn_failures > int(rt_config.rpc_retries):
                    raise exc.ObjectLostError(
                        hex_, f"owner unreachable ({e})"
                    )
                if deadline is not None and time.monotonic() >= deadline:
                    raise exc.GetTimeoutError(
                        f"get() timed out pulling {hex_}"
                    )
                await asyncio.sleep(retry.next_delay())
            except protocol.RpcError as e:
                raise exc.ObjectLostError(hex_, str(e))
        if hh.get("kind") == "shm":
            return ("shm", hh["meta"])
        if hh.get("kind") == "dev":
            # Device-plane object whose directory entry was missed (e.g.
            # a dropped registration): the owner's spec routes the getter
            # to the device pull.
            return ("dev", hh["spec"])
        if hh.get("kind") == "err":
            return ("err", _loads_maybe(frames))
        return ("mem", frames)

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        # Caller-thread fast path: a ref whose entry is already in the local
        # store is ready by definition, and store reads are thread-safe. The
        # dominant wait() shape (all or enough refs already ready — pure
        # bookkeeping) answers with k dict probes and ZERO loop hops,
        # futures, or RPCs; only a genuinely pending tail pays the async
        # machinery.
        store = self.memory_store
        ready: List[ObjectRef] = []
        not_ready: List[ObjectRef] = []
        for r in refs:
            (ready if r._id._bytes.hex() in store else not_ready).append(r)
        if len(ready) >= num_returns or not not_ready:
            return ready, not_ready
        return self.run_sync(self._wait(refs, num_returns, timeout))

    async def _wait(self, refs, num_returns, timeout):
        # Partition synchronously first: probe futures are spawned ONLY for
        # genuinely pending refs (never one per ref), and every pending
        # remote ref shares one batched poller instead of polling the
        # directory per-ref.
        ready: List[ObjectRef] = []
        pending: List[ObjectRef] = []
        my_addr = tuple(self.addr or ())
        for r in refs:
            if r._id._bytes.hex() in self.memory_store:
                ready.append(r)
            else:
                pending.append(r)
        deadline = None if timeout is None else time.monotonic() + timeout
        tasks: Dict[Any, ObjectRef] = {}
        pollers: List[asyncio.Task] = []
        if len(ready) < num_returns and pending:
            # hex -> [futures]: duplicate refs in one wait() share the id
            # but need one future each (tasks is keyed by future).
            remote_futs: Dict[str, List[Any]] = {}
            by_owner: Dict[tuple, List[str]] = {}
            for r in pending:
                owner = tuple(r.owner_address or ())
                if owner == my_addr:
                    tasks[asyncio.ensure_future(
                        self._local_ready_probe(r)
                    )] = r
                else:
                    fut = self.loop.create_future()
                    hex_ = r._id._bytes.hex()
                    tasks[fut] = r
                    lst = remote_futs.get(hex_)
                    if lst is None:
                        remote_futs[hex_] = lst = []
                        by_owner.setdefault(owner, []).append(hex_)
                    lst.append(fut)
            if remote_futs:
                pollers.append(asyncio.ensure_future(
                    self._remote_ready_poll(remote_futs, by_owner)
                ))
        try:
            while len(ready) < num_returns and tasks:
                tmo = None if deadline is None else max(deadline - time.monotonic(), 0)
                done, _ = await asyncio.wait(
                    tasks.keys(), timeout=tmo, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break
                for d in done:
                    ref = tasks.pop(d)
                    err = d.exception()
                    if err is not None:
                        # The probe failed (e.g. owner unreachable): surface it
                        # as a ready-with-error object so get() reports it.
                        self.memory_store.setdefault(
                            ref.id().hex(), ("err", err)
                        )
                    ready.append(ref)
        finally:
            for t in tasks:
                t.cancel()
            for p in pollers:
                p.cancel()
        ready_set = {id(r) for r in ready}
        not_ready = [r for r in refs if id(r) not in ready_set]
        return ready, not_ready

    async def _local_ready_probe(self, ref: ObjectRef):
        hex_ = ref.id().hex()
        if hex_ not in self.memory_store:
            await self._wait_local(hex_, None)
        return True

    async def _remote_ready_poll(self, remote_futs: Dict[str, List[Any]],
                                 by_owner: Dict[tuple, List[str]]):
        """ONE poller for every pending remote ref in a wait(): each cycle
        issues a single object_lookup_batch for all unresolved oids plus one
        contains_object_batch per owner still holding unresolved inline
        objects — O(owners) RPCs per cycle, not O(refs). Resolves the
        per-ref futures the wait loop selects on (duplicate refs share one
        remote_futs slot holding each copy's future). Must never die with
        futures unresolved — a probe failure becomes a ready-with-error
        result, matching the per-ref probe contract."""
        def settle(hex_, err=None):
            for fut in remote_futs.pop(hex_, []):
                if not fut.done():
                    if err is not None:
                        fut.set_exception(err)
                    else:
                        fut.set_result(True)

        try:
            await self._remote_ready_poll_inner(remote_futs, by_owner,
                                               settle)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # A poller crash must not strand the wait loop: surface the
            # failure on every remaining ref (the old per-ref probes
            # reported exceptions the same way, one ref at a time).
            for hex_ in list(remote_futs):
                settle(hex_, exc.ObjectLostError(hex_, f"probe failed: {e!r}"))

    async def _remote_ready_poll_inner(self, remote_futs, by_owner, settle):
        from ray_tpu._private.config import rt_config

        while remote_futs:
            for hex_ in [h for h in remote_futs if h in self.memory_store]:
                settle(hex_)
            if not remote_futs:
                return
            oids = list(remote_futs)
            try:
                h, _ = await self._head_call(
                    "object_lookup_batch", {"oids": oids}
                )
                for oid, meta in zip(oids, h.get("metas") or []):
                    if meta is not None:
                        settle(oid)
            except (protocol.RpcError, protocol.ConnectionLost, OSError) as e:
                # Directory unavailable: owner probes still decide.
                logger.debug("wait() directory poll failed: %s", e)
            for owner, hexes in list(by_owner.items()):
                hexes = [x for x in hexes if x in remote_futs]
                by_owner[owner] = hexes
                if not hexes:
                    del by_owner[owner]
                    continue
                if not owner:
                    # No owner address to probe and the directory has no
                    # entry: nothing can ever report this ref ready.
                    for hex_ in hexes:
                        settle(hex_, exc.ObjectLostError(
                            hex_, "no owner address on ref"
                        ))
                    del by_owner[owner]
                    continue
                try:
                    conn = await self.get_peer(owner)
                    # Deadline-bounded probe: a dropped probe reply costs
                    # one cycle, not the whole wait() (next cycle re-asks).
                    hh, _ = await asyncio.wait_for(
                        conn.call(
                            "contains_object_batch", {"oids": hexes}
                        ),
                        float(rt_config.rpc_deadline_s),
                    )
                    for hex_, rdy in zip(hexes, hh.get("ready") or []):
                        if rdy:
                            settle(hex_)
                except asyncio.TimeoutError:
                    continue
                except (protocol.ConnectionLost, ConnectionRefusedError,
                        OSError):
                    for hex_ in hexes:
                        settle(hex_, exc.ObjectLostError(
                            hex_, "owner unreachable"
                        ))
                    del by_owner[owner]
                except protocol.RpcError as e:
                    # Owner can't answer the probe: surface as ready-with-
                    # error (the old per-ref probe let this propagate the
                    # same way) instead of spinning two failing RPCs every
                    # cycle forever.
                    for hex_ in hexes:
                        settle(hex_, exc.ObjectLostError(
                            hex_, f"owner probe failed: {e}"
                        ))
                    del by_owner[owner]
            if remote_futs:
                await asyncio.sleep(0.005)

    def as_future(self, ref: ObjectRef) -> SyncFuture:
        return asyncio.run_coroutine_threadsafe(self._get_one(ref, None), self.loop)

    def as_asyncio_future(self, ref: ObjectRef):
        """Awaitable from ANY event loop. _get_one must run on the core loop:
        its store_events Events are set() by the core loop, and a cross-loop
        Event.wait() never wakes once the waiter's loop goes idle."""
        async def _get():
            cfut = asyncio.run_coroutine_threadsafe(
                self._get_one(ref, None), self.loop
            )
            v = await asyncio.wrap_future(cfut)
            if isinstance(v, Exception):
                raise v
            return v
        return _get()

    # -------------------------------------------------------- task submission

    # Serialized ((), [], {}) — the no-arg call shape — computed once. Tasks
    # and actor calls with no arguments are the dominant control-plane shape
    # (reference microbenchmark shapes are all no-arg), and re-pickling an
    # empty tuple per call costs more than the whole wire framing.
    _EMPTY_ARGS_FRAMES: Optional[List[bytes]] = None

    def _serialize_args(self, args, kwargs, split: bool = False):
        """Top-level ObjectRef args are passed by reference and materialized by
        the executor (reference semantics); nested refs ride along as borrows.

        With ``split`` (plain-task submit while arg interning is on),
        plain args whose serialized form could plausibly repeat across
        tasks get their OWN frame section appended after the skeleton —
        the returned ``an`` lists each section's frame count (wire key
        ``an``). The shared config dict of a parameter sweep then
        produces byte-identical frames on every push, which is exactly
        what the per-peer :class:`specframe.ArgLedger` digests; varying
        scalars keep riding the skeleton inline."""
        if not args and not kwargs:
            frames = CoreWorker._EMPTY_ARGS_FRAMES
            if frames is None:
                frames = CoreWorker._EMPTY_ARGS_FRAMES = self.ctx.serialize(
                    ((), [], {})
                ).to_frames()
            return list(frames), [], [], None
        arg_slots = []
        ref_ids = []
        plain = []
        sep = []
        for a in args:
            if isinstance(a, ObjectRef):
                arg_slots.append(("ref", len(ref_ids)))
                ref_ids.append((a.id().hex(), list(a.owner_address or ())))
            elif split and _intern_worthy(a):
                arg_slots.append(("sv", len(sep)))
                sep.append(a)
            else:
                arg_slots.append(("val", len(plain)))
                plain.append(a)

        def _ser():
            sk = self.ctx.serialize((arg_slots, plain, kwargs))
            return sk, [self.ctx.serialize(a) for a in sep]

        ((sk, sobjs), nested) = collect_refs_during(_ser)
        frames = sk.to_frames()
        an = None
        if sobjs:
            an = []
            for so in sobjs:
                fr = so.to_frames()
                an.append(len(fr))
                frames.extend(fr)
        borrows = list(ref_ids) + [
            (r.id().hex(), list(r.owner_address or ())) for r in nested
        ]
        self._add_borrows(borrows)
        return frames, ref_ids, borrows, an

    def _spec_template(self, fn, fkey, name, retries) -> Optional[bytes]:
        """The pre-framed invariant spec for (function, options): packed
        ONCE, spliced into every push_task wire message as frame 0 so the
        per-call header carries only deltas (tid/fkey/nret/argrefs). None
        = caller uses the inline full-header path (template build failed,
        or this process has no address yet)."""
        key = (fkey, name, retries)
        tmpl = self._spec_templates.get(key)
        if tmpl is not None:
            return tmpl
        if self.addr is None:
            return None
        try:
            if faultpoints.ACTIVE:
                # error: framing degrades to the inline header — the spec
                # cache is an optimization, never a correctness gate.
                faultpoints.fire("worker.spec.frame")
            fl = flight.ENABLED
            if fl:
                fl_t0 = time.monotonic()
            tmpl = specframe.pack_spec({
                "owner": list(self.addr),
                "name": name or getattr(fn, "__name__", "task"),
                "renv": self._prepare_runtime_env(None),
                # executing side reads this for kill policy (a pressure
                # kill must prefer tasks the owner will actually retry)
                "retries": retries,
            })
        except Exception as e:
            logger.debug("spec template for %s failed (inline header): %s",
                         fkey[:8], e)
            return None
        if len(self._spec_templates) >= 512:
            self._spec_templates.clear()  # tiny + rebuildable
        self._spec_templates[key] = tmpl
        self._stats["spec_templates_built"] += 1
        if fl:
            flight.record("worker.spec.frame", fkey[:12], "worker", fl_t0,
                          time.monotonic(), len(tmpl), "ok")
        return tmpl

    def submit_task(
        self,
        fn,
        args,
        kwargs,
        *,
        num_returns=1,
        resources: Optional[Dict[str, float]] = None,
        strategy: Optional[dict] = None,
        max_retries: int = 3,
        name: str = "",
        runtime_env: Optional[dict] = None,
    ):
        """Returns a list of ObjectRefs, or a StreamingObjectRefGenerator
        when num_returns == "streaming" (reference: generator tasks,
        ``task_manager.h`` streaming returns)."""
        streaming = num_returns == "streaming"
        fl = flight.ENABLED
        if fl:
            fl_t0 = time.monotonic()
        fkey = self.export_function(fn)
        task_id = TaskID.of()
        # Per-arg framing rides only the plain-task push path (the one
        # _arg_intern_wire digests); actor calls keep the single skeleton.
        frames, ref_ids, borrow_ids, an = self._serialize_args(
            args, kwargs, split=self._arg_interning
        )
        if not resources and not strategy:
            # Hot path: the shared default dict + precomputed sched key skip
            # a dict copy and a sorted-tuple build per call. Never mutated
            # downstream (_LeaseSet holds it read-only).
            resources, strategy, skey = (
                self._DEFAULT_RESOURCES, {}, self._DEFAULT_SCHED_KEY
            )
        else:
            resources = dict(resources or {"CPU": 1})
            strategy = strategy or {}
            skey = None
        # Pre-framed spec fast path: everything invariant per (function,
        # options) rides a cached template frame; streaming and explicit
        # runtime envs keep the authoritative inline path.
        tmpl = (
            self._spec_template(fn, fkey, name, max_retries)
            if not streaming and runtime_env is None else None
        )
        if tmpl is not None:
            header = {
                "tid": task_id.hex(),
                "fkey": fkey,
                "nret": num_returns,
                "sp": 1,
            }
            if ref_ids:
                header["argrefs"] = ref_ids
            if borrow_ids:
                header["borrows"] = borrow_ids
            frames = [tmpl] + frames
        else:
            header = {
                "tid": task_id.hex(),
                "fkey": fkey,
                "nret": -1 if streaming else num_returns,
                "argrefs": ref_ids,
                "borrows": borrow_ids,
                "owner": list(self.addr),
                "name": name or getattr(fn, "__name__", "task"),
                "renv": self._prepare_runtime_env(runtime_env),
                "retries": max_retries,
            }
        if an:
            header["an"] = an
        from ray_tpu.util.tracing import tracing_helper

        if tracing_helper.enabled():
            header["trace"] = tracing_helper.inject_context()
        if streaming:
            # A re-executed generator would re-emit items: no retries.
            max_retries = 0
            self._task_streams[task_id.hex()] = {"count": None, "produced": 0}
        pp = self._pack_plane
        refs = []
        if not streaming:
            for i in range(num_returns):
                oid = ObjectID.for_return(task_id, i)
                self._register_owned(oid.hex())
                refs.append(ObjectRef(oid, tuple(self.addr)))
            if pp is None:
                # Pack plane on -> lineage bookkeeping moves to the pack
                # thread (_pack_drain); it is already called from
                # arbitrary caller threads, so the thread home changes,
                # not the race discipline.
                self._record_lineage(
                    task_id.hex(), header, frames, resources, strategy,
                    num_returns,
                )
        self._stats["tasks_submitted"] += 1
        if fl:
            # Taskpath plane: the submit span (serialize/export/enqueue)
            # plus the queued stamp the pusher turns into a task.queued
            # span at pop time ("_tq" never reaches the wire — popped
            # there). The readable name rides the header so spec-framed
            # submissions still attribute per function.
            if "name" not in header:
                header["name"] = name or getattr(fn, "__name__", "task")
            now = time.monotonic()
            taskpath.record_phase(
                "submit", header["tid"], fl_t0, now,
                fn=header["name"], phase="submit",
            )
            header["_tq"] = now
        packed = False
        if pp is not None:
            # Round 20 pack plane: per-task wire-size accounting, lineage
            # bookkeeping and the dispatch enqueue leave this caller
            # thread; the plane feeds the loop whole pre-packed batches
            # (one loop wakeup and one lease pump per burst, not per
            # task). error/drop from the driver.submit.pack faultpoint —
            # and a full plane queue — degrade THIS submission to the
            # inline path below: the task is never lost, only
            # un-offloaded.
            ok = True
            if faultpoints.ACTIVE:
                try:
                    ok = faultpoints.fire("driver.submit.pack") != "drop"
                except Exception:
                    ok = False
            packed = ok and pp.offer(
                (header, frames, resources, strategy, max_retries, skey,
                 streaming, num_returns)
            )
        if not packed:
            if pp is not None and not streaming:
                # The plane rejected the handoff: make up the deferred
                # lineage record inline before dispatch.
                self._record_lineage(
                    task_id.hex(), header, frames, resources, strategy,
                    num_returns,
                )
            self._enqueue_dispatch(
                self._dispatch_task_fast, (header, frames, resources,
                                           strategy, max_retries, skey)
            )
        if streaming:
            from ray_tpu.object_ref import StreamingObjectRefGenerator

            return StreamingObjectRefGenerator(self, task_id, tuple(self.addr))
        return refs

    def _enqueue_dispatch(self, coro_fn, args: tuple):
        """Queue (coro_fn, args) for task creation on the core loop, waking
        the loop at most once per burst of submissions."""
        with self._submit_lock:
            self._submit_buf.append((coro_fn, args))
            if self._submit_scheduled:
                return
            self._submit_scheduled = True
        self.loop.call_soon_threadsafe(self._drain_submits)

    def _drain_submits(self):
        try:
            while True:
                with self._submit_lock:
                    batch, self._submit_buf = self._submit_buf, []
                    if not batch:
                        self._submit_scheduled = False
                        return
                for coro_fn, args in batch:
                    try:
                        # NB: bound methods are re-created per attribute
                        # access: compare the underlying function.
                        if getattr(coro_fn, "__func__", None) is (
                            CoreWorker._dispatch_task_fast
                        ):
                            # Hot path: plain enqueue + callback; a retry
                            # coroutine is built only on failure.
                            coro_fn(*args)
                        else:
                            spawn_logged(self.loop, coro_fn(*args),
                                         "worker.submit_drain")
                    except Exception as e:
                        # One bad submission fails ITS task; it must not
                        # wedge the drain (a stuck _submit_scheduled flag
                        # would silently stop all future submissions).
                        try:
                            self._fail_task(
                                args[0], exc.RayTpuError(repr(e))
                            )
                        except Exception:
                            logger.exception("submit drain failure")
        except BaseException:
            with self._submit_lock:
                self._submit_scheduled = False
            raise

    _DEFAULT_RESOURCES = {"CPU": 1}
    _DEFAULT_SCHED_KEY = ((("CPU", 1),), ())

    def _dispatch_task_fast(self, header, frames, resources, strategy,
                            retries, skey=None):
        key = skey if skey is not None else self._sched_key(
            resources, strategy
        )
        lease_set = self.leases.get(key)
        if lease_set is None:
            lease_set = _LeaseSet(resources, strategy)
            self.leases[key] = lease_set
        fut = self.loop.create_future()
        # 4th element: wire-size estimate, computed ONCE at enqueue — the
        # pack loop used to re-sum the head item's frames on every peek
        # (O(frames) per loop iteration even when the first peek fit).
        lease_set.pending.append(
            (header, frames, fut, sum(len(fr) for fr in frames) + 4096)
        )
        self._pump_leases(key, lease_set)
        fut.add_done_callback(
            self._dispatch_retry_cb(header, frames, resources, strategy,
                                    retries)
        )

    def _dispatch_retry_cb(self, header, frames, resources, strategy,
                           retries):
        """Done-callback for a dispatch future: failure spawns the retry
        coroutine (shared by the inline fast path and the round-20
        pack-plane drain)."""

        def done(f):
            if f.cancelled():
                return
            e = f.exception()
            if e is not None:
                spawn_logged(
                    self.loop,
                    self._dispatch_retry(
                        header, frames, resources, strategy, retries, e
                    ),
                    "worker.dispatch_retry",
                )

        return done

    def _pack_drain(self, batch):
        """Pack-plane worker (round 20), PLANE-THREAD side: the per-task
        submit work that needs neither the caller nor the loop — wire-size
        estimation over every frame, lineage bookkeeping — happens here,
        and the whole batch re-enters the loop as ONE scheduled call."""
        out = []
        for (header, frames, resources, strategy, retries, skey,
             streaming, nret) in batch:
            if not streaming:
                self._record_lineage(header["tid"], header, frames,
                                     resources, strategy, nret)
            out.append(
                (header, frames, resources, strategy, retries, skey,
                 sum(len(fr) for fr in frames) + 4096)
            )
        try:
            self.loop.call_soon_threadsafe(self._drain_packed_on_loop, out)
        except RuntimeError:
            pass  # loop closed (shutdown); dispatch futures never existed

    def _drain_packed_on_loop(self, batch):
        """Loop-side apply of a pack-plane batch: create every dispatch
        future in one pass, then ONE lease pump per scheduling key — the
        inline path pumps once per task."""
        pumped = {}
        for header, frames, resources, strategy, retries, skey, size \
                in batch:
            key = skey if skey is not None else self._sched_key(
                resources, strategy
            )
            lease_set = self.leases.get(key)
            if lease_set is None:
                lease_set = _LeaseSet(resources, strategy)
                self.leases[key] = lease_set
            fut = self.loop.create_future()
            lease_set.pending.append((header, frames, fut, size))
            fut.add_done_callback(
                self._dispatch_retry_cb(header, frames, resources,
                                        strategy, retries)
            )
            pumped[key] = lease_set
        for key, lease_set in pumped.items():
            self._pump_leases(key, lease_set)

    async def _dispatch_retry(self, header, frames, resources, strategy,
                              retries, first_err):
        """Continue a failed first dispatch attempt: same retry policy as
        _dispatch_task_inner, entered only on failure."""
        try:
            err = first_err
            attempt = 0
            while (
                isinstance(err, exc.WorkerCrashedError) and attempt < retries
            ):
                if isinstance(err, exc.OutOfMemoryError):
                    await asyncio.sleep(min(0.5 * 2 ** attempt, 5.0))
                if faultpoints.ACTIVE:
                    await faultpoints.async_fire("worker.dispatch.retry")
                attempt += 1
                key = self._sched_key(resources, strategy)
                lease_set = self.leases.get(key)
                if lease_set is None:
                    lease_set = _LeaseSet(resources, strategy)
                    self.leases[key] = lease_set
                fut = self.loop.create_future()
                lease_set.pending.append(
                    (header, frames, fut,
                     sum(len(fr) for fr in frames) + 4096)
                )
                self._pump_leases(key, lease_set)
                try:
                    await fut
                    return
                except exc.RayTpuError as e:
                    err = e
            raise err
        except Exception as e:
            self._fail_task(
                header,
                e if isinstance(e, exc.RayTpuError) else exc.RayTpuError(
                    repr(e)
                ),
            )

    def _prepare_runtime_env(self, runtime_env: Optional[dict]) -> dict:
        """Submit-side runtime-env preparation: local py_modules paths are
        zipped and staged in the head KV once (content-addressed) so every
        executor fetches the same bits (reference: packaging.py upload)."""
        if not runtime_env:
            return {}
        from ray_tpu._private import runtime_env as renv_mod

        renv_mod.validate(runtime_env)
        if runtime_env.get("py_modules"):
            from ray_tpu._private.runtime_env import packaging

            runtime_env = dict(
                runtime_env,
                py_modules=packaging.stage_modules(
                    self, runtime_env["py_modules"]
                ),
            )
        hook = runtime_env.get("worker_process_setup_hook")
        if callable(hook):
            # Callables cannot ride the msgpack task header: pickle at
            # submit time (reference: setup_hook.py exports the hook via
            # the function table).
            import cloudpickle

            runtime_env = dict(
                runtime_env,
                worker_process_setup_hook={
                    "__pickled_hook__": cloudpickle.dumps(hook).hex()
                },
            )
        return runtime_env

    def _sched_key(self, resources, strategy):
        return (
            tuple(sorted(resources.items())),
            tuple(sorted((k, str(v)) for k, v in strategy.items())),
        )

    def _fail_task(self, header, err: Exception):
        tid = TaskID.from_hex(header["tid"])
        if header["nret"] == -1:
            # streaming: the failure becomes the final item so consumers
            # iterate up to it and then raise
            rec = self._task_streams.get(header["tid"])
            produced = rec.get("produced", 0) if rec else 0
            self._store_error(
                ObjectID.for_return(tid, produced).hex(), err
            )
            if rec is not None:
                rec["count"] = produced + 1
                rec["failed_idx"] = produced
                ev = rec.get("event")
                if ev is not None:
                    ev.set()
            self._release_borrows(header.get("borrows", []))
            return
        for i in range(header["nret"]):
            self._store_error(ObjectID.for_return(tid, i).hex(), err)
        self._release_borrows(header.get("borrows", []))

    # In-flight pushes per leased slot: depth 2 keeps the next task on the
    # wire while the current one executes (the worker's executor queues it),
    # hiding the push RPC latency. Depth 1 caps throughput at
    # slots/round-trip; real parallelism stays bounded by the worker's own
    # task slots (reference: pipelined task submission on leased workers).
    _PUSH_PIPELINE = 16

    def _pump_leases(self, key, lease_set: _LeaseSet):
        lease_set.last_active = time.monotonic()
        # Spawn long-lived pushers (≤ _PUSH_PIPELINE per slot), each draining
        # the pending queue — per-task create_task churn would dominate the
        # driver loop at high rates.
        # Spawn at most one new pusher per queued item this pass — but never
        # count busy pushers as capacity for NEW work: each is committed to
        # its in-flight task for that task's whole runtime, and treating it
        # as available would strand queued tasks while other slots idle
        # (deadlock for producer/consumer task patterns).
        # Slot pick is a rotating cursor, not min-by-busy: a min() scan is
        # O(slots) per queued item, and zero-resource tasks can hold dozens
        # of slots (measured 6.8M lambda calls on the queued-1M leg). The
        # cursor finds the first non-draining slot with pusher headroom;
        # one full rotation with no pick means every slot is saturated.
        spawn_budget = len(lease_set.pending)
        slots = lease_set.slots
        while spawn_budget > 0 and slots and not lease_set.saturated:
            n = len(slots)
            slot = None
            for off in range(n):
                s = slots[(lease_set.rr + off) % n]
                # With an adaptive window, pushers beyond what the
                # window can feed would only park on the rendezvous
                # event — cap spawn at window/chunk (+1 for ramp
                # headroom) so a shrunk slot runs 2-3 pushers, not 16
                # parked coroutines churning the loop.
                cap = self._PUSH_PIPELINE
                if s.pwin is not None:
                    cap = min(
                        cap, s.pwin.window // self._PUSH_BATCH + 1
                    )
                if not s.draining and s.busy < cap:
                    slot = s
                    lease_set.rr = (lease_set.rr + off + 1) % n
                    break
            if slot is None:
                lease_set.saturated = True
                break
            slot.busy += 1
            spawn_budget -= 1
            shard = self._shard_loop_for(slot)
            if shard is None:
                spawn_logged(self.loop,
                             self._slot_pusher(key, lease_set, slot),
                             "worker.slot_pusher")
            else:
                # Round 20: this slot's pushers live on its shard loop
                # (peer-address affinity — a slot's chunks never
                # interleave across loops, so its PushWindow and
                # win_event stay single-loop).
                spawn_threadsafe(shard,
                                 self._slot_pusher(key, lease_set, slot),
                                 "worker.slot_pusher")
        # Only the items NOT covered by a pusher spawned this pass warrant
        # new leases (requesting one per queued item would strand surplus
        # slots at the head until the reaper returns them — an idle surplus
        # slot pins a CPU and starves e.g. a nested task's lease).
        need = spawn_budget
        if need > 0 and not lease_set.requesting:
            lease_set.requesting = True
            spawn_logged(self.loop,
                         self._request_leases(key, lease_set, min(need, 64)),
                         "worker.request_leases")
        # Whenever slots are held, exactly one reaper must be alive to return
        # them once idle (grants can arrive after the queue already drained).
        if lease_set.slots and not lease_set.reaper_running:
            lease_set.reaper_running = True
            spawn_logged(self.loop, self._lease_reaper(key, lease_set),
                         "worker.lease_reaper")

    async def _request_leases(self, key, lease_set: _LeaseSet, count):
        from ray_tpu._private.config import rt_config

        try:
            now = time.monotonic()
            lease_set.avoid = {
                n: t for n, t in lease_set.avoid.items() if t > now
            }
            # The head may block up to lease_request_timeout_s waiting for
            # resources, so the per-attempt RPC deadline sits above that
            # window. corr: a retry after a dropped GRANT reply replays
            # the original grants instead of double-acquiring capacity.
            wait_s = float(rt_config.lease_request_timeout_s)
            h, _ = await self._head_call(
                "lease",
                {
                    "resources": lease_set.resources,
                    "strategy": lease_set.strategy,
                    "count": count,
                    "timeout": wait_s,
                    "avoid": list(lease_set.avoid),
                },
                timeout=wait_s + max(float(rt_config.rpc_deadline_s), 2.0),
                corr=True,
            )
            for g in h.get("grants", []):
                lease_set.slots.append(
                    _LeaseSlot(g["node_id"], tuple(g["addr"]))
                )
            if h.get("grants"):
                lease_set.saturated = False
                lease_set.last_grant_t = time.monotonic()
                lease_set.last_grant_warm = any(
                    g.get("warm") for g in h["grants"]
                )
        except (protocol.RpcError, protocol.ConnectionLost, OSError) as e:
            logger.warning("lease request failed: %s", e)
            # fail pending tasks if nothing can ever be granted
            if not lease_set.slots:
                for item in lease_set.pending:
                    fut = item[2]
                    if not fut.done():
                        fut.set_exception(
                            exc.RayTpuError(f"lease request failed: {e}")
                        )
                lease_set.pending.clear()
        finally:
            lease_set.requesting = False
            self._pump_leases(key, lease_set)

    # Tasks per wire message on the ring transport: one encode/send/wakeup
    # amortizes the whole chunk (each sub-task still replies, fails, and
    # retries individually).
    _PUSH_BATCH = 16

    def _pusher_node_lost(self, lease_set, slot, futs):
        """Node died mid-push: drop its slots and fail the affected futures
        so their dispatch retries elsewhere. The dropped slots are RETURNED
        to the head: if the node really died the release is a tolerated
        no-op (its record is gone), but after a mere connection failure the
        head would otherwise count the capacity as leased forever — this
        driver's ledger only drains on disconnect."""
        doomed = [s for s in lease_set.slots if s.node_id == slot.node_id]
        lease_set.slots = [
            s for s in lease_set.slots if s.node_id != slot.node_id
        ]
        lease_set.saturated = False
        # A successor process at this address starts with an empty function
        # cache AND an empty interned-arg cache: both must be re-covered.
        self._fn_push.forget_peer(slot.addr)
        self._arg_ledger.forget_peer(slot.addr)
        for s in doomed:
            self._release_slot(lease_set, s)
        for fut in futs:
            if not fut.done():
                fut.set_exception(
                    exc.WorkerCrashedError(f"node {slot.node_id[:8]} lost")
                )

    def _pusher_rpc_error(self, lease_set, slot, fut, e) -> bool:
        """Handle a per-task RpcError; True when the slot must stop (oom)."""
        if fut.done():
            return False
        if getattr(e, "code", None) == "oom":
            # Memory-pressure rejection: retriable, and this node's slots
            # are RETURNED to the head (the node is alive — dropping them
            # silently would leak its resource accounting). Idle slots
            # release now; in-flight ones drain first (releasing a busy
            # slot would double-book the node).
            lease_set.avoid[slot.node_id] = time.monotonic() + 10.0
            keep = []
            for s in lease_set.slots:
                if s.node_id != slot.node_id:
                    keep.append(s)
                elif s.busy > 0:
                    s.draining = True
                    keep.append(s)
                else:
                    self._release_slot(lease_set, s)
            lease_set.slots = keep
            fut.set_exception(exc.OutOfMemoryError(str(e)))
            return True
        fut.set_exception(exc.RayTpuError(str(e)))
        return False

    def _fn_push_wire(self, addr, header, frames):
        """Function push-through: on the FIRST push of an fkey to this
        peer, splice the function blob into the wire message (flag ``fb``,
        frame after the spec) so the executing worker installs it from the
        push instead of round-tripping a kv_get to the head. Returns the
        (possibly augmented) wire header/frames; the queued originals are
        never mutated (a requeued task must re-decide for its next peer)."""
        fkey = header.get("fkey")
        if not fkey or "fb" in header:
            return header, frames
        blob = self._fn_push.blob_for(addr, fkey)
        if blob is None:
            return header, frames
        h2 = dict(header)
        h2["fb"] = 1
        if header.get("sp"):
            return h2, [frames[0], blob, *frames[1:]]
        return h2, [blob, *frames]

    def _arg_intern_wire(self, addr, header, frames):
        """Per-peer argument interning at wire-build time: each small arg
        frame is content-hashed; a digest this peer already holds is
        OMITTED from the wire (header key ``ai`` = [[pos, digest]...] in
        arg-frame positions) while a first-seen digest ships its bytes
        and asks the executor to intern them (``aib``). The queued
        originals are never mutated — a requeued task re-decides for its
        next peer, exactly like ``_fn_push_wire``."""
        if not self._arg_interning:
            return header, frames
        if faultpoints.ACTIVE:
            # error: this push degrades to full frames (interning is an
            # optimization, never a correctness gate). drop: the peer's
            # coverage is reset — every blob re-ships, exercising
            # re-cover exactly like a slot loss would.
            try:
                if faultpoints.fire("worker.arg.intern") == "drop":
                    self._arg_ledger.forget_peer(addr)
            except Exception as e:
                logger.debug("arg interning degraded to full frames: %s", e)
                return header, frames
        start = 1 if header.get("sp") else 0
        min_b, max_b = self._arg_intern_min, self._arg_intern_max
        ai = None
        aib = None
        wire = None
        for pos in range(start, len(frames)):
            f = frames[pos]
            n = len(f)
            if n < min_b or n > max_b:
                if wire is not None:
                    wire.append(f)
                continue
            digest = hashlib.blake2b(f, digest_size=16).digest()
            if wire is None:
                wire = list(frames[:pos])
            if self._arg_ledger.covered(addr, digest):
                # Peer holds these bytes: send the digest, keep the frame
                # home. O(unique args) arg bytes per (peer, burst).
                if ai is None:
                    ai = []
                ai.append([pos - start, digest])
                self._stats["arg_frames_interned"] += 1
                self._stats["arg_intern_bytes_saved"] += n
            else:
                if aib is None:
                    aib = []
                aib.append([pos - start, digest])
                wire.append(f)
                self._stats["arg_blobs_pushed"] += 1
        if wire is None or (ai is None and aib is None):
            return header, frames
        h2 = dict(header)
        if ai:
            h2["ai"] = ai
        if aib:
            h2["aib"] = aib
        return h2, wire

    def _task_wire(self, addr, header, frames):
        """Wire form of one queued push for one peer: interned argument
        frames first (positions are arg-relative, so the later splices
        don't disturb them), then the function push-through blob."""
        h2, f2 = self._arg_intern_wire(addr, header, frames)
        return self._fn_push_wire(addr, h2, f2)

    def _pop_pending(self, lease_set: _LeaseSet) -> tuple:
        """Pop the next pending task, turning its submit-time "_tq" stamp
        into a ``task.queued`` span whose outcome NAMES the wait: a grant
        that landed after enqueue means the task sat on a lease
        (lease-wait — cold worker spawns surface here too, the head
        blocks the grant until capacity exists), a warm-tagged grant
        names the warm-pool activation, otherwise it was plain
        submit-queue depth. The stamp never reaches the wire.

        Queue items carry a 4th element — the enqueue-time wire-size
        estimate the pack loop peeks at — which is dropped here: chunks
        stay (header, frames, fut) triples for every downstream path."""
        item = lease_set.pending.popleft()
        header = item[0]
        if self._reply_batching and "corr" not in header:
            # Per-task correlation id (the task id — already unique per
            # logical task): arms receiver-side dedup, so a deadline-
            # re-armed re-push after a dropped reply window replays the
            # recorded outcome instead of executing twice.
            header["corr"] = header["tid"]
        t_enq = header.pop("_tq", None)
        if t_enq is not None and flight.ENABLED:
            if lease_set.last_grant_t <= t_enq:
                tag = "submit-queue"
            elif lease_set.last_grant_warm:
                tag = "warm-pool-hit"
            else:
                tag = "lease-wait"
            taskpath.record_phase(
                "queued", header.get("tid"), t_enq, time.monotonic(),
                fn=header.get("name") or header.get("fkey", "")[:10],
                outcome=tag, phase=tag,
            )
        return item[:3] if len(item) > 3 else item

    async def _call_with_tcp_fallback(self, conn, addr, method, header, frames):
        """Issue an RPC on ``conn`` (usually a ring); when the encoded
        message exceeds the ring limit despite the caller's size
        pre-estimate, retry once over TCP to the same address. Server-side
        seq admission tolerates mixed transports. Callable from shard
        loops: ``_conn_call``/``_peer_on_loop`` marshal the TCP legs to
        the driver loop (round 20)."""
        try:
            return await self._conn_call(conn, method, header, frames)
        except MessageTooBig:
            tcp = await self._peer_on_loop(addr)
            return await self._conn_call(tcp, method, header, frames)

    async def _await_chunk_settled(self, rfs, conn, addr, chunk):
        """Settle EVERY reply future of one pushed chunk under a shared
        deadline: ONE ``asyncio.wait`` (one timer) covers the whole
        chunk per attempt window, instead of a per-task
        ``asyncio.wait_for`` — per-task timers were a measured drag on
        the saturated driver loop at 100k+ queued tasks, and chunk-mates
        settle together anyway (their replies ride coalesced frames).
        On a deadline, every straggler is cancelled and re-pushed under
        its SAME corr id with jittered backoff — receiver-side dedup
        replays or attaches, never re-executes. Returns the (possibly
        re-issued) future list; every entry is done. Per-item errors
        (incl. the typed ``arg_intern_miss``) stay in the futures for
        the caller's in-order processing."""
        rfs = list(rfs)
        pending_idx = [i for i, rf in enumerate(rfs) if not rf.done()]
        attempt_s = self._push_deadline_s
        rearm = None
        while pending_idx:
            await asyncio.wait({rfs[i] for i in pending_idx},
                               timeout=attempt_s)
            pending_idx = [i for i in pending_idx if not rfs[i].done()]
            if not pending_idx:
                break
            if rearm is None:
                rearm = Backoff(base=0.05, cap=2.0)
            await asyncio.sleep(rearm.next_delay())
            for i in pending_idx:
                rfs[i].cancel()  # the re-push's reply is the live one
                header, frames, _fut = chunk[i]
                wh, wf = self._task_wire(addr, header, frames)
                rfs[i] = asyncio.ensure_future(
                    self._call_with_tcp_fallback(
                        conn, addr, "push_task", wh, wf
                    )
                )
        return rfs

    async def _await_push_reply(self, rf, conn, addr, header, frames):
        """Await one push_task reply. Without a corr id (reply batching
        off) this is the plain unbounded wait. With one, the wait is
        deadline-bounded the way actor pushes already are: silence (a
        dropped coalesced reply frame, a lost push) re-issues the SAME
        corr with jittered backoff — receiver-side dedup replays the
        recorded outcome or attaches to the in-flight execution, never
        re-runs the task; a long-running task just keeps re-arming. A
        typed ``arg_intern_miss`` (receiver evicted an interned frame)
        resets the peer's coverage and re-pushes the exact bytes."""
        corr = header.get("corr")
        if not corr:
            return await rf
        attempt_s = self._push_deadline_s
        rearm = None
        while True:
            try:
                if asyncio.isfuture(rf) and rf.done():
                    # Chunk-mates settle together (their replies ride one
                    # coalesced frame), so by the time the in-order await
                    # loop reaches this item its reply usually already
                    # landed with a sibling's — skip the deadline timer;
                    # result() raises exactly what await would.
                    return rf.result()
                return await asyncio.wait_for(rf, attempt_s)
            except asyncio.TimeoutError:
                if rearm is None:
                    rearm = Backoff(base=0.05, cap=2.0)
                await asyncio.sleep(rearm.next_delay())
                wh, wf = self._task_wire(addr, header, frames)
                rf = self._call_with_tcp_fallback(
                    conn, addr, "push_task", wh, wf
                )
            except protocol.RpcError as e:
                if getattr(e, "code", None) != "arg_intern_miss":
                    raise
                self._stats["arg_intern_miss_retries"] += 1
                self._arg_ledger.forget_peer(addr)
                # Re-push with FULL argument frames (no interning): the
                # receiver re-interns from the ``aib``-less wire and the
                # bytes reaching deserialize are the submitter's exactly.
                wh, wf = self._fn_push_wire(addr, header, frames)
                rf = self._call_with_tcp_fallback(
                    conn, addr, "push_task", wh, wf
                )

    async def _win_acquire(self, lease_set, slot):
        """Acquire push-window capacity on ``slot`` before packing a
        chunk. Returns ``(max_tasks, win)``: ``win`` is None when pacing
        is off for this chunk (gate, or the ``worker.push.window``
        faultpoint degraded it to the fixed fan-out) and ``max_tasks``
        is then the static batch cap. A full window parks this pusher on
        the slot's rendezvous event — sibling settles/releases set it —
        with a short safety horizon re-check so a release lost to an
        error path can never park a pusher forever. Returns ``(0, win)``
        when the slot or queue went away while parked (the caller's
        loop re-checks its own conditions)."""
        if not self._push_window:
            return self._PUSH_BATCH, None
        if faultpoints.ACTIVE:
            try:
                act = await faultpoints.async_fire("worker.push.window")
            except Exception as e:
                # error kind: THIS chunk degrades to the fixed pre-pacing
                # fan-out — the window is an optimization, never a
                # correctness gate.
                logger.debug("push-window pacing degraded: %s", e)
                return self._PUSH_BATCH, None
            if act == "drop" and slot.pwin is not None:
                slot.pwin.reset()  # cold re-ramp from the floor
        win = slot.pwin
        if win is None:
            win = slot.pwin = specframe.PushWindow(
                initial=self._pwin_initial, floor=self._pwin_floor,
                ceiling=self._pwin_ceiling,
                latency_factor=self._pwin_factor,
            )
            slot.win_event = asyncio.Event()
        # Grant quantum: accept at least half a chunk (clamped by the
        # window itself) — a nearly-full window parks this pusher
        # instead of fragmenting the burst into 1-2 task messages.
        want = self._PUSH_BATCH
        min_g = min(want, max(1, win.window // 2))
        n = win.grant(want, min_g)
        while n <= 0:
            if (slot.draining or not lease_set.pending
                    or slot not in lease_set.slots):
                return 0, win
            ev = slot.win_event
            ev.clear()
            min_g = min(want, max(1, win.window // 2))
            n = win.grant(want, min_g)  # re-check: no missed wake
            if n > 0:
                break
            self._stats["push_window_waits"] += 1
            try:
                await asyncio.wait_for(ev.wait(), 1.0)
            except asyncio.TimeoutError:
                logger.debug("push window full on %s for 1s; re-checking",
                             slot.node_id[:8])
            min_g = min(want, max(1, win.window // 2))
            n = win.grant(want, min_g)
        return n, win

    def _win_settled(self, slot, win, n, latency_s):
        """One chunk settled: feed the AIMD update and wake any pusher
        parked on the slot's window."""
        if not win.on_settled(n, latency_s):
            self._stats["push_window_shrinks"] += 1
        ev = slot.win_event
        if ev is not None:
            ev.set()

    def _win_release(self, slot, win, n):
        """Return grant capacity without a pacing signal (chunk packed
        smaller than granted, transport error paths)."""
        if win is None or n <= 0:
            return
        win.release(n)
        ev = slot.win_event
        if ev is not None:
            ev.set()

    def _record_pump_queue(self, tid, h, now):
        """Driver-side ``pump-queue`` phase: a reply frame's dwell
        between transport arrival (the ``_fr`` stamp the ring pump /
        TCP recv loop writes on reply headers) and this settle — both
        ends on the driver's clock, skew-free. Under saturation this is
        the settle queueing that used to hide inside derived reply-ack;
        sub-threshold dwell stays there (same discipline as the
        reply-window phase: recording tax only where there is truth to
        record)."""
        arr = h.get("_fr")
        if arr is None:
            return
        sq = h.get("_sq")
        if sq is not None and sq > arr:
            # Round 20: the settle plane carved this dwell in two —
            # arrival->handoff is still transport-side pump queueing,
            # handoff->settle is the plane's own dwell (its queue depth
            # plus the cross-loop hop). Both carry the same recording
            # threshold; whichever halves stay sub-threshold land in
            # derived reply-ack exactly as before.
            if sq - arr >= _WINDOW_DWELL_MIN_S:
                taskpath.record_phase("pump_queue", tid, arr, sq,
                                      phase="pump-queue")
            if now - sq >= _WINDOW_DWELL_MIN_S:
                taskpath.record_phase("settle_dwell", tid, sq, now,
                                      phase="settle-dwell")
        elif now - arr >= _WINDOW_DWELL_MIN_S:
            taskpath.record_phase("pump_queue", tid, arr, now,
                                  phase="pump-queue")

    # ---------------------------------------------------------- round 20:
    # pusher-loop sharding. Slots hash onto N dedicated event loops by
    # peer address; everything a pusher touches that is driver-loop state
    # (lease bookkeeping, dispatch futures, TCP connections, the owned-
    # object store behind _handle_task_reply) marshals through the
    # helpers below. Slot affinity is the invariant that keeps the rest
    # single-loop: ONE peer's slots always land on ONE shard, so a
    # slot's push window, rendezvous event, and chunk ordering never
    # interleave across loops.

    def _shard_loop_for(self, slot):
        """Pick the pusher loop for ``slot`` by peer-address hash.
        Returns None when sharding is off (pushers stay on the driver
        loop). First pick is recorded on the slot; a later disagreement
        (the shard pool never changes mid-run, so this means a bug)
        counts ``pusher_shard_affinity_breaks`` and re-pins."""
        loops = self._pusher_loops
        if not loops:
            return None
        loop = loops[hash(slot.addr) % len(loops)]
        if slot.shard_loop is None:
            slot.shard_loop = loop
        elif slot.shard_loop is not loop:
            self._stats["pusher_shard_affinity_breaks"] += 1
            slot.shard_loop = loop
        return loop

    async def _main_coro(self, coro):
        """Await ``coro`` on the DRIVER loop from a shard loop. The
        cross-loop hop pair (schedule + wake) is the whole cost; results
        and exceptions propagate unchanged."""
        return await asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(coro, self.loop)
        )

    async def _main_sync(self, fn, *args):
        """Run a synchronous callable on the driver loop and await its
        return value from a shard loop (``_pusher_rpc_error`` needs the
        verdict before the pusher can decide to stop)."""
        cf: SyncFuture = SyncFuture()

        def _run():
            try:
                cf.set_result(fn(*args))
            except BaseException as e:  # propagate to the awaiting shard
                cf.set_exception(e)

        self.loop.call_soon_threadsafe(_run)
        return await asyncio.wrap_future(cf)

    async def _peer_on_loop(self, addr):
        """``get_peer`` from whatever loop the caller runs on: TCP
        connections live on the driver loop (their ``_pending`` map is
        loop-thread-only), so shard callers marshal the lookup."""
        if asyncio.get_running_loop() is self.loop:
            return await self.get_peer(addr)
        return await self._main_coro(self.get_peer(addr))

    async def _ring_on_loop(self, addr):
        """``get_ring`` with the same cross-loop discipline as
        ``_peer_on_loop`` (the ring cache and dial are driver-loop
        state; the returned ring itself is cross-loop-callable)."""
        if asyncio.get_running_loop() is self.loop:
            return await self.get_ring(addr)
        return await self._main_coro(self.get_ring(addr))

    async def _conn_call(self, conn, method, header, frames):
        """Issue ``conn.call`` from whatever loop the caller runs on.
        Ring connections are cross-loop-safe (pending under ``_plock``,
        reply futures settle on the calling loop); a TCP Connection's
        pending map is owned by the driver loop, so shard-loop callers
        marshal the whole call through it."""
        from ray_tpu._private.ringconn import RingConnection

        if (asyncio.get_running_loop() is self.loop
                or isinstance(conn, RingConnection)):
            return await conn.call(method, header, frames)
        return await self._main_coro(conn.call(method, header, frames))

    def _pop_pending_locked(self, lease_set):
        """Pop one pending item under the lease set's pack lock, or None
        when the queue drained first. With sharded pushers, slots of ONE
        lease set can pack on different loops concurrently — the
        peek/pop in the pack loop must be atomic against siblings."""
        with lease_set.plock:
            if not lease_set.pending:
                return None
            return self._pop_pending(lease_set)

    def _chunk_settle_on_loop(self, items):
        """Settle one pushed chunk's replies on the driver loop: shard
        pushers collect ``(header, reply_header, reply_frames, fut)``
        per chunk and flush them here in ONE cross-loop hop —
        ``_handle_task_reply``'s owned-object/stream bookkeeping and the
        dispatch futures are driver-loop state."""
        for header, h, rframes, fut in items:
            try:
                self._handle_task_reply(header, h, rframes)
            except Exception:
                logger.exception("task reply settle failed")
            if not fut.done():
                fut.set_result(None)

    def _pusher_exit_on_loop(self, key, lease_set, slot):
        """A pusher's exit bookkeeping (busy decrement, drain release,
        re-pump) — always on the driver loop; shard pushers marshal
        their outer ``finally`` here."""
        slot.busy = max(slot.busy - 1, 0)
        lease_set.saturated = False
        if slot.busy == 0:
            slot.idle_since = time.monotonic()
        if slot.draining and slot.busy == 0:
            if slot in lease_set.slots:
                lease_set.slots.remove(slot)
                self._release_slot(lease_set, slot)
        lease_set.last_active = time.monotonic()
        if lease_set.pending:
            self._pump_leases(key, lease_set)

    async def _slot_pusher(self, key, lease_set, slot):
        """Drains pending tasks onto one leased slot until the queue (or the
        slot) is gone; many tasks amortize one coroutine. On the ring
        transport a chunk of pending tasks rides one wire message.
        In-flight depth is paced by the slot's adaptive push window
        (``_win_acquire``): each packed chunk holds window capacity from
        push to settle, and the settle latency is the window's AIMD
        clock.

        Round 20: with ``pusher_loop_shards`` on, this coroutine runs on
        a SHARD loop (peer-address affinity). Transport I/O, window
        pacing, and reply awaiting all stay here; driver-loop state —
        lease bookkeeping, dispatch futures, TCP connections, reply
        settling — marshals through the ``*_on_loop`` helpers. A chunk's
        settles flush in ONE cross-loop hop (the per-iteration finally),
        so the driver loop pays O(chunks), not O(tasks)."""
        my_loop = asyncio.get_running_loop()
        on_shard = my_loop is not self.loop
        shard_idx = (self._pusher_loops.index(my_loop)
                     if on_shard and my_loop in self._pusher_loops else -1)
        try:
            while (lease_set.pending and slot in lease_set.slots
                   and not slot.draining):
                chunk: List[tuple] = []
                settles: List[tuple] = []  # shard mode: (hdr, h, fr, fut)
                fut = None
                win = None
                held = 0  # window capacity this pusher holds (releases
                # in the iteration's finally on every error path)
                fl_t0 = time.monotonic()  # refined once the chunk is built
                try:
                    ring = await self._ring_on_loop(slot.addr)
                    if not lease_set.pending:
                        break  # drained by a sibling pusher during the await
                    granted, win = await self._win_acquire(lease_set, slot)
                    if granted <= 0:
                        continue  # slot/queue changed while parked
                    if win is not None:
                        held = granted
                    if not lease_set.pending:
                        break  # drained while parked on the window
                    if ring is None:
                        conn = await self._peer_on_loop(slot.addr)
                        if not lease_set.pending:
                            break
                        it = self._pop_pending_locked(lease_set)
                        if it is not None:
                            chunk = [it]
                    else:
                        conn = ring
                        # Pack tasks up to the granted window, the batch
                        # count, and the ring's message budget; a task too
                        # big for the ring rides TCP instead (same node,
                        # same semantics). The whole peek/pop pass holds
                        # the pack lock (no awaits inside): sharded
                        # siblings of this lease set pack concurrently.
                        budget = ring.max_msg - 65536
                        size = 0
                        oversize = False
                        with lease_set.plock:
                            while (lease_set.pending
                                   and len(chunk) < granted):
                                it = lease_set.pending[0]
                                # Enqueue-time size estimate (4th element);
                                # the O(frames) re-sum per peek is gone.
                                sz = it[3] if len(it) > 3 else sum(
                                    len(fr) for fr in it[1]
                                ) + 4096
                                if sz > budget:
                                    oversize = not chunk
                                    break
                                if size + sz > budget and chunk:
                                    break
                                size += sz
                                chunk.append(self._pop_pending(lease_set))
                        if oversize:
                            conn = await self._peer_on_loop(slot.addr)
                            it = self._pop_pending_locked(lease_set)
                            if it is not None:
                                chunk = [it]
                    if not chunk:
                        continue
                    if shard_idx >= 0:
                        # Single-writer per shard (slot affinity): no lock.
                        st = self._pusher_shard_stats[shard_idx]
                        st["chunks"] += 1
                        st["tasks"] += len(chunk)
                    if held > len(chunk):
                        # Packed fewer than granted (queue drained, byte
                        # budget): the surplus goes back to siblings now.
                        self._win_release(slot, win, held - len(chunk))
                        held = len(chunk)
                    t_send = time.monotonic()
                    fl = flight.ENABLED
                    if fl:
                        fl_t0 = time.monotonic()
                        fl_bytes = sum(
                            len(fr) for _h, fs, _f in chunk for fr in fs
                        )
                    if faultpoints.ACTIVE:
                        # error = ConnectionLost into the outer handler:
                        # slots dropped + released, every chunk future
                        # fails as WorkerCrashedError, dispatch retries.
                        await faultpoints.async_fire(
                            "worker.task.push", err=protocol.ConnectionLost
                        )
                    if len(chunk) == 1:
                        header, frames, fut = chunk[0]
                        wh, wf = self._task_wire(slot.addr, header, frames)
                        h, rframes = await self._await_push_reply(
                            self._call_with_tcp_fallback(
                                conn, slot.addr, "push_task", wh, wf
                            ),
                            conn, slot.addr, header, frames,
                        )
                        if on_shard:
                            settles.append((header, h, rframes, fut))
                        else:
                            self._handle_task_reply(header, h, rframes)
                        t_now = time.monotonic()
                        if win is not None:
                            # AIMD clock: push -> reply ARRIVAL at the
                            # transport, not -> this coroutine running —
                            # a saturated driver loop's settle queueing
                            # is pump-queue, not executor congestion.
                            self._win_settled(
                                slot, win, 1,
                                (h.get("_fr") or t_now) - t_send,
                            )
                            held = 0
                        if not on_shard and not fut.done():
                            fut.set_result(None)
                        if fl:
                            # Span covers push → reply, i.e. dispatch +
                            # execution on the leased slot.
                            flight.record("worker.task.push",
                                          header.get("tid"), "worker",
                                          fl_t0, t_now, fl_bytes, "ok")
                            taskpath.record_phase(
                                "push", header.get("tid"), fl_t0, t_now,
                                nbytes=fl_bytes,
                            )
                            self._record_pump_queue(
                                header.get("tid"), h, t_now
                            )
                        continue

                    try:
                        rfuts = conn.call_batch(
                            "push_task",
                            [self._task_wire(slot.addr, h, f)
                             for h, f, _ in chunk],
                        )
                    except MessageTooBig:
                        # Frame-size estimate missed (oversized headers):
                        # push each task alone; singles that still exceed
                        # the ring ride TCP. Futures must never be dropped.
                        for i, (header, frames, fut) in enumerate(chunk):
                            try:
                                h, rframes = await self._await_push_reply(
                                    self._call_with_tcp_fallback(
                                        conn, slot.addr, "push_task",
                                        header, frames,
                                    ),
                                    conn, slot.addr, header, frames,
                                )
                                if on_shard:
                                    settles.append(
                                        (header, h, rframes, fut)
                                    )
                                else:
                                    self._handle_task_reply(
                                        header, h, rframes
                                    )
                                if fl:
                                    taskpath.record_phase(
                                        "push", header.get("tid"), fl_t0,
                                        time.monotonic(),
                                    )
                                if not on_shard and not fut.done():
                                    fut.set_result(None)
                            except protocol.RpcError as e:
                                if on_shard:
                                    stop_now = await self._main_sync(
                                        self._pusher_rpc_error,
                                        lease_set, slot, fut, e,
                                    )
                                else:
                                    stop_now = self._pusher_rpc_error(
                                        lease_set, slot, fut, e
                                    )
                                if stop_now:
                                    # This slot is done (e.g. OOM eviction);
                                    # the rest of the chunk goes back to the
                                    # queue for other slots — their futures
                                    # must not be abandoned. Re-stamp the
                                    # enqueue-time size estimate the pack
                                    # loop peeks at.
                                    with lease_set.plock:
                                        lease_set.pending.extend(
                                            (h2, f2, fu2,
                                             sum(len(fr) for fr in f2)
                                             + 4096)
                                            for h2, f2, fu2 in chunk[i + 1:]
                                        )
                                    if on_shard:
                                        self.loop.call_soon_threadsafe(
                                            self._pump_leases,
                                            key, lease_set,
                                        )
                                    else:
                                        self._pump_leases(key, lease_set)
                                    return
                        if win is not None:
                            self._win_settled(slot, win, len(chunk),
                                              time.monotonic() - t_send)
                            held = 0
                        continue
                    stop = False
                    arr_max = 0.0  # latest reply ARRIVAL (AIMD clock)
                    for i, ((header, frames, fut), rf) in enumerate(
                        zip(chunk, rfuts)
                    ):
                        try:
                            if (asyncio.isfuture(rf) and rf.done()
                                    and not rf.cancelled()
                                    and rf.exception() is None):
                                # Chunk-mates settle together (coalesced
                                # reply frames): skip the await wrapper
                                # — and its coroutine — entirely for the
                                # common already-settled case. Errors
                                # keep the full path (deadline re-arm,
                                # intern-miss re-push).
                                h, rframes = rf.result()
                            else:
                                h, rframes = await self._await_push_reply(
                                    rf, conn, slot.addr, header, frames
                                )
                        except protocol.ConnectionLost:
                            doomed = [c[2] for c in chunk[i:]]
                            if on_shard:
                                self.loop.call_soon_threadsafe(
                                    self._pusher_node_lost,
                                    lease_set, slot, doomed,
                                )
                            else:
                                self._pusher_node_lost(
                                    lease_set, slot, doomed
                                )
                            return
                        except protocol.RpcError as e:
                            if on_shard:
                                if await self._main_sync(
                                    self._pusher_rpc_error,
                                    lease_set, slot, fut, e,
                                ):
                                    stop = True
                            elif self._pusher_rpc_error(
                                lease_set, slot, fut, e
                            ):
                                stop = True
                            continue
                        if on_shard:
                            settles.append((header, h, rframes, fut))
                        else:
                            self._handle_task_reply(header, h, rframes)
                        arr = h.get("_fr")
                        if arr is not None and arr > arr_max:
                            arr_max = arr
                        if fl:
                            # Per-task push envelope (cid = task id): the
                            # chunk-level worker.task.push verb span stays
                            # for RPC attribution; this one anchors the
                            # task's driver-clock wall time.
                            t_now = time.monotonic()
                            taskpath.record_phase(
                                "push", header.get("tid"), fl_t0, t_now,
                            )
                            self._record_pump_queue(
                                header.get("tid"), h, t_now
                            )
                        if not on_shard and not fut.done():
                            fut.set_result(None)
                    if win is not None:
                        # AIMD clock: push -> last reply ARRIVAL; the
                        # arrival->settle dwell is driver-side queueing
                        # (pump-queue), not executor congestion.
                        self._win_settled(
                            slot, win, len(chunk),
                            (arr_max or time.monotonic()) - t_send,
                        )
                        held = 0
                    if fl:
                        flight.record("worker.task.push",
                                      chunk[0][0].get("tid"), "worker",
                                      fl_t0, time.monotonic(), fl_bytes,
                                      f"ok:batch{len(chunk)}")
                    if stop:
                        return
                except (protocol.ConnectionLost, ConnectionRefusedError,
                        OSError):
                    if flight.ENABLED and chunk:
                        flight.record("worker.task.push",
                                      chunk[0][0].get("tid"), "worker",
                                      fl_t0, time.monotonic(), 0,
                                      "error:ConnectionLost")
                    doomed = [c[2] for c in chunk]
                    if on_shard:
                        self.loop.call_soon_threadsafe(
                            self._pusher_node_lost, lease_set, slot, doomed
                        )
                    else:
                        self._pusher_node_lost(lease_set, slot, doomed)
                    return
                except protocol.RpcError as e:
                    if fut is not None:
                        if on_shard:
                            if await self._main_sync(
                                self._pusher_rpc_error,
                                lease_set, slot, fut, e,
                            ):
                                return
                        elif self._pusher_rpc_error(
                            lease_set, slot, fut, e
                        ):
                            return
                finally:
                    # Window capacity must not leak on ANY exit (errors,
                    # node loss, oversize fallback) — a leaked grant
                    # shrinks the slot's effective window forever.
                    if held:
                        self._win_release(slot, win, held)
                        held = 0
                    if settles:
                        # ONE cross-loop hop settles the whole chunk
                        # (shard mode only appends here). Ordering vs a
                        # node-lost marshal above is FIFO on the driver
                        # loop, and the two cover disjoint futures.
                        self.loop.call_soon_threadsafe(
                            self._chunk_settle_on_loop, settles
                        )
        finally:
            if on_shard:
                self.loop.call_soon_threadsafe(
                    self._pusher_exit_on_loop, key, lease_set, slot
                )
            else:
                self._pusher_exit_on_loop(key, lease_set, slot)

    async def _lease_reaper(self, key, lease_set: _LeaseSet):
        """Return idle leases to the head (reference: lease idle timeout in
        NormalTaskSubmitter). One reaper per lease set. Release is
        PER-SLOT: a slot idle >0.5s goes back even while a sibling slot
        runs a long task — an idle surplus slot pins node resources the
        head could grant to someone else (nested tasks deadlock otherwise)."""
        try:
            while True:
                await asyncio.sleep(0.25)
                if not lease_set.slots and not lease_set.pending:
                    return
                if lease_set.pending:
                    continue
                now = time.monotonic()
                keep = []
                for s in lease_set.slots:
                    if (
                        s.busy == 0
                        and now - s.idle_since > 0.5
                        and now - lease_set.last_active > 0.5
                    ):
                        self._release_slot(lease_set, s)
                    else:
                        keep.append(s)
                lease_set.slots = keep
        finally:
            lease_set.reaper_running = False

    def _reclaim_idle_leases(self):
        """Head-requested lease reclamation (reference: raylet returns
        leased workers on demand when the cluster is resource-starved).
        Every cached slot with no in-flight task goes back immediately;
        sets with queued work keep theirs."""
        for lease_set in self.leases.values():
            if lease_set.pending:
                continue
            keep = []
            for s in lease_set.slots:
                if s.busy == 0:
                    self._release_slot(lease_set, s)
                else:
                    keep.append(s)
            lease_set.slots = keep

    def _release_slot(self, lease_set: _LeaseSet, slot: _LeaseSlot):
        if slot.pwin is not None:
            # Retire the slot's window stats so bench/tests still see
            # peak/grow/shrink economics after the lease reaper returns
            # the slot (bounded: one entry per peer address).
            self._fold_pwin_stats(slot)
        try:
            self.gcs.notify(
                "release_lease",
                {
                    "node_id": slot.node_id,
                    "resources": lease_set.resources,
                    "strategy": lease_set.strategy,
                },
            )
        except protocol.ConnectionLost as e:
            logger.debug("release_lease for node %s dropped, head gone: %s",
                         slot.node_id, e)

    def _fold_pwin_stats(self, slot):
        """Fold one released slot's push-window counters into the
        retired-per-peer table (max for window/peak, sums for the event
        counters)."""
        snap = slot.pwin.snapshot()
        peer = f"{slot.addr[0]}:{slot.addr[1]}"
        cur = self._pwin_retired.get(peer)
        if cur is None:
            self._pwin_retired[peer] = snap
            return
        cur["window"] = max(cur["window"], snap["window"])
        cur["peak"] = max(cur["peak"], snap["peak"])
        for k in ("grows", "shrinks", "settled"):
            cur[k] += snap[k]

    def transit_stats(self) -> dict:
        """Transit-plane pacing snapshot for bench/tests: per-peer push
        windows (live slots merged with retired ones), the ring pump's
        drain batch-size histogram (served rings — the executor side of
        every same-host peer), and frames-settled-per-recv-wakeup for
        the TCP driver loop. Pure snapshot-time reads; no locks beyond
        what the underlying counters already hold."""
        push: Dict[str, dict] = {
            peer: dict(snap) for peer, snap in self._pwin_retired.items()
        }
        for ls in self.leases.values():
            for s in ls.slots:
                if s.pwin is None:
                    continue
                snap = s.pwin.snapshot()
                peer = f"{s.addr[0]}:{s.addr[1]}"
                cur = push.get(peer)
                if cur is None:
                    push[peer] = snap
                    continue
                cur["window"] = max(cur["window"], snap["window"])
                cur["peak"] = max(cur["peak"], snap["peak"])
                for k in ("grows", "shrinks", "settled"):
                    cur[k] += snap[k]
        pump = {"drains": 0, "msgs": 0, "batch_hist": {}}
        rings = [r for r in self._served_rings if not r._closed]
        rings += [
            r for r in self._ring_peers.values()
            if r and not getattr(r, "_closed", True)
        ]
        for r in rings:
            st = getattr(r, "pump_stats", None)
            if not st:
                continue
            pump["drains"] += st.get("drains", 0)
            pump["msgs"] += st.get("msgs", 0)
            for k, v in st.get("batch_hist", {}).items():
                key = str(k)
                pump["batch_hist"][key] = (
                    pump["batch_hist"].get(key, 0) + v
                )
        settle = {"wakeups": 0, "frames": 0, "drained": 0, "max_batch": 0}
        conns = list(self.peers.values()) + rings
        if self.gcs is not None:
            conns.append(self.gcs)
        for c in conns:
            st = getattr(c, "settle_stats", None)
            if not st:
                continue
            settle["wakeups"] += st.get("wakeups", 0)
            settle["frames"] += st.get("frames", 0)
            settle["drained"] += st.get("drained", 0)
            settle["max_batch"] = max(
                settle["max_batch"], st.get("max_batch", 0)
            )
        out = {
            "node_id": self.node_id,
            "push_window": push,
            "pump": pump,
            "settle": settle,
        }
        # Round 20 planes: present only when the gate created them, so
        # gates-off snapshots stay byte-identical to round 19's.
        if self._settle_plane is not None:
            out["settle_plane"] = self._settle_plane.snapshot()
        if self._pack_plane is not None:
            out["pack_plane"] = self._pack_plane.snapshot()
        if self._pusher_shard_stats:
            out["pusher_shards"] = [
                dict(s) for s in self._pusher_shard_stats
            ]
        return out

    def _handle_task_reply(self, header, h, rframes):
        """Process a push_task reply: inline values, shm descriptors, errors."""
        tid = TaskID.from_hex(header["tid"])
        self._release_borrows(header.get("borrows", []))
        if h.get("stream"):
            rec = self._task_streams.get(header["tid"])
            if rec is not None:
                rec["count"] = h.get("count", 0)
                ev = rec.get("event")
                if ev is not None:
                    ev.set()
                if rec.get("abandoned"):
                    self._task_streams.pop(header["tid"], None)
            return
        rets = h.get("rets", [])
        cursor = 0
        for i, r in enumerate(rets):
            oid = ObjectID.for_return(tid, i).hex()
            if r["kind"] == "mem":
                n = r["nframes"]
                self.memory_store[oid] = ("mem", rframes[cursor : cursor + n])
                cursor += n
            elif r["kind"] == "shm":
                self.memory_store[oid] = ("shm", r["meta"])
            elif r["kind"] == "err":
                n = r["nframes"]
                err = self.ctx.deserialize_frames(rframes[cursor : cursor + n])
                cursor += n
                self.memory_store[oid] = ("err", err)
            nested = r.get("nested")
            if nested:
                # The executing worker pinned borrows for refs inside this
                # return value; freeing the return object must release
                # them (owned[oid]["nested"] rides the same path put()'s
                # nested refs do).
                rec = self.owned.get(oid)
                if rec is not None:
                    rec.setdefault("nested", [])
                    rec["nested"] = list(rec["nested"]) + [
                        (e[0], e[1]) for e in nested
                    ]
                else:
                    # Fire-and-forget: the caller already dropped the
                    # return ref. Re-registering would resurrect it with a
                    # count nobody decrements — release the executor's
                    # borrow credits instead.
                    self._release_borrows([(e[0], e[1]) for e in nested])
            ev = self.store_events.get(oid)
            if ev is not None:
                ev.set()

    # ---------------------------------------------------------------- actors

    def create_actor(
        self,
        cls,
        args,
        kwargs,
        *,
        resources: Optional[Dict[str, float]] = None,
        strategy: Optional[dict] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        concurrency_groups: Optional[Dict[str, int]] = None,
        method_meta: Optional[Dict[str, int]] = None,
        name: Optional[str] = None,
        namespace: str = "default",
        get_if_exists: bool = False,
        runtime_env: Optional[dict] = None,
        lifetime: Optional[str] = None,
    ):
        if lifetime not in (None, "detached"):
            raise ValueError(
                f"lifetime must be None or 'detached', got {lifetime!r}"
            )
        actor_id = ActorID.of(self.job_id)
        cls_key = self.export_function(cls)
        frames, ref_ids, borrows, _an = self._serialize_args(args, kwargs)
        header = {
            "actor_id": actor_id.hex(),
            "class_key": cls_key,
            "class_name": getattr(cls, "__name__", "Actor"),
            "resources": resources or {"CPU": 1},
            "strategy": strategy or {},
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "name": name,
            "namespace": namespace,
            "get_if_exists": get_if_exists,
            "lifetime": lifetime,
            "method_meta": method_meta or {},
            # env_vars/working_dir/py_modules apply to the hosted actor;
            # pip/uv actor isolation (a dedicated venv-worker per actor)
            # is not supported — validate() rejects unknown plugins and
            # construct() raises on pip/uv below.
            "renv": self._prepare_runtime_env(runtime_env),
        }
        # creation_frames replayed on restart: [spec-pickle, arg frames...].
        # argrefs live in the spec so restart replays resolve them again.
        spec = cloudpickle.dumps(
            {
                "class_key": cls_key,
                "max_concurrency": header["max_concurrency"],
                "concurrency_groups": concurrency_groups,
                "renv": header["renv"],
                "argrefs": ref_ids,
            }
        )
        from ray_tpu._private.config import rt_config

        if (
            name is None
            and not get_if_exists
            and lifetime != "detached"
            and bool(rt_config.actor_create_batch)
        ):
            # Deferred batched creation (reference: async actor
            # registration — creation errors surface on the handle's
            # first use, not at .remote()): the caller gets the handle
            # immediately; a burst of N creations coalesces into
            # O(bursts) create_actor_batch head RPCs, and the batch
            # reply primes the actor channel's address so the first
            # method push skips the alive-polling round trips. Named /
            # get_if_exists / detached creations need their reply
            # synchronously and keep the per-actor verb.
            self._enqueue_actor_create(
                actor_id.hex(), header, [spec] + frames, borrows
            )
            return actor_id, None, False
        try:
            # Non-idempotent: corr-dedup at the head makes a retry after a
            # dropped reply return the FIRST creation's placement instead
            # of creating a second actor; a retry that beats a slow
            # schedule attaches to the in-flight execution.
            h = self.run_sync(
                self._head_call(
                    "create_actor", header, [spec] + frames, corr=True,
                )
            )[0]
        finally:
            # Creation args were materialized (or creation failed); drop the
            # borrow pins. Restart replay re-fetches refs best-effort — if the
            # owner freed them by then the restart fails (round-1 limitation;
            # the reference pins lineage for restartable actors instead).
            self.loop.call_soon_threadsafe(self._release_borrows, borrows)
        if "existing" in h:
            info = h["existing"]
            addr = tuple(info["addr"]) if info.get("addr") else None
            return ActorID.from_hex(info["actor_id"]), addr, True
        return actor_id, tuple(h["addr"]), False

    # Deferred creations per create_actor_batch RPC. Batches are
    # self-clocking: at most ONE batch RPC is in flight per worker, so the
    # first creation flushes at once (latency-optimal) and everything
    # enqueued during its round trip rides the next batch (throughput-
    # optimal) — same shape as the protocol layer's write coalescing.
    _ACREATE_BATCH = 256

    def _enqueue_actor_create(self, aid: str, header: dict,
                              frames: List[bytes], borrows: list):
        pc = _PendingActorCreate(aid, header, frames, borrows)
        self._actor_creating[aid] = pc
        with self._acreate_lock:
            self._acreate_buf.append(pc)
            if self._acreate_scheduled or self._acreate_inflight:
                return
            self._acreate_scheduled = True
        self.loop.call_soon_threadsafe(self._drain_actor_creates)

    def _drain_actor_creates(self):
        """Flush one batch of deferred creations (loop thread)."""
        with self._acreate_lock:
            self._acreate_scheduled = False
            if self._acreate_inflight or not self._acreate_buf:
                return
            batch = self._acreate_buf[: self._ACREATE_BATCH]
            del self._acreate_buf[: self._ACREATE_BATCH]
            self._acreate_inflight = True
        for pc in batch:
            pc.fut = self.loop.create_future()
        spawn_logged(self.loop, self._send_actor_create_batch(batch),
                     "worker.actor_create_batch")

    async def _send_actor_create_batch(self, batch):
        try:
            counts, flat = protocol.pack_multi_frames(
                [pc.frames for pc in batch]
            )
            # corr covers the WHOLE batch: a retry after a dropped reply
            # replays every item's original outcome (head dispatch dedup),
            # so no actor is ever created twice.
            h, _ = await self._head_call(
                "create_actor_batch",
                {"items": [pc.header for pc in batch], "fcounts": counts},
                flat, corr=True,
            )
            results = list(h.get("results") or ())
            for pc, res in zip(batch, results):
                if res.get("ok"):
                    addr = tuple(res.get("addr") or ()) or None
                    self._finish_actor_create(pc, addr=addr)
                else:
                    self._finish_actor_create(
                        pc, err=res.get("err") or "actor creation failed"
                    )
            for pc in batch[len(results):]:
                self._finish_actor_create(
                    pc, err="create_actor_batch reply truncated"
                )
        except Exception as e:
            for pc in batch:
                self._finish_actor_create(
                    pc, err=f"create_actor_batch failed: {e}"
                )
        finally:
            with self._acreate_lock:
                self._acreate_inflight = False
                more = bool(self._acreate_buf)
                if more:
                    self._acreate_scheduled = True
            if more:
                self.loop.call_soon(self._drain_actor_creates)

    def _finish_actor_create(self, pc: _PendingActorCreate,
                             addr=None, err: Optional[str] = None):
        """Resolve one deferred creation (loop thread): prime or poison
        the actor channel, release the arg borrows, wake every waiter."""
        ch = self.get_actor_channel(pc.aid, addr)
        if err is not None:
            ch.dead = True
            ch.death_reason = err
        elif addr is not None and ch.addr is None:
            ch.addr = tuple(addr)
        self._actor_creating.pop(pc.aid, None)
        self._release_borrows(pc.borrows)
        pc.error = err
        if pc.fut is not None and not pc.fut.done():
            pc.fut.set_result(None)
        pc.event.set()

    def ensure_actor_created(self, aid_hex: str, timeout: float = 30.0):
        """Block (caller threads only) until a locally-enqueued deferred
        creation for this actor has reached the head. Used before the
        handle crosses a process boundary (serialization) and before
        kill — a peer resolving the handle via the head must find the
        actor registered. No-op for non-pending actors; never blocks an
        event-loop thread (the receiver-side not-found grace covers the
        remaining window)."""
        pc = self._actor_creating.get(aid_hex)
        if pc is None:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pc.event.wait(timeout)

    def get_actor_channel(self, actor_id_hex: str, addr=None) -> _ActorChannel:
        ch = self.actor_channels.get(actor_id_hex)
        if ch is None:
            ch = _ActorChannel(actor_id_hex, addr)
            self.actor_channels[actor_id_hex] = ch
        return ch

    def submit_actor_task(
        self,
        actor_id_hex: str,
        method_name: str,
        args,
        kwargs,
        *,
        num_returns: int = 1,
        max_task_retries: int = 0,
        concurrency_group: Optional[str] = None,
    ) -> List[ObjectRef]:
        if num_returns == "streaming":
            raise ValueError(
                "num_returns='streaming' is not supported for actor "
                "methods (only plain tasks); return a list, or move the "
                "generator into a task"
            )
        fl = flight.ENABLED
        if fl:
            fl_t0 = time.monotonic()
        task_id = TaskID.of(ActorID.from_hex(actor_id_hex))
        frames, ref_ids, borrow_ids, _an = self._serialize_args(args, kwargs)
        header = {
            "tid": task_id.hex(),
            "aid": actor_id_hex,
            "method": method_name,
            "nret": num_returns,
            "argrefs": ref_ids,
            "borrows": borrow_ids,
            "owner": list(self.addr),
            "caller": self.worker_id.hex(),
        }
        if concurrency_group is not None:
            header["cg"] = concurrency_group
        refs = []
        for i in range(num_returns):
            oid = ObjectID.for_return(task_id, i)
            self._register_owned(oid.hex())
            refs.append(ObjectRef(oid, tuple(self.addr)))
        self._stats["tasks_submitted"] += 1
        if fl:
            # Taskpath plane: submit span + the queued stamp the dispatch
            # loop turns into a task.queued span at first push ("_tq" is
            # popped there, never sent).
            now = time.monotonic()
            taskpath.record_phase(
                "submit", header["tid"], fl_t0, now, fn=method_name,
                phase="submit",
            )
            header["_tq"] = now
        self._enqueue_dispatch(
            self._dispatch_actor_task, (header, frames, max_task_retries)
        )
        return refs

    async def _dispatch_actor_task(self, header, frames, retries):
        try:
            await self._dispatch_actor_task_inner(header, frames, retries)
        except Exception as e:
            # Nothing may escape unresolved: every return ref must settle.
            self._fail_task(
                header, e if isinstance(e, exc.RayTpuError) else exc.RayTpuError(repr(e))
            )

    async def _dispatch_actor_task_inner(self, header, frames, retries):
        from ray_tpu._private.config import rt_config

        ch = self.get_actor_channel(header["aid"])
        # Submit-time queued stamp (popped here — must not ride the wire):
        # becomes the task.queued span once the first push goes out.
        t_enq = header.pop("_tq", None)
        # One correlation id per LOGICAL call, shared by every delivery
        # attempt: the hosting worker dedups on it, so a reply dropped
        # AFTER the method ran is replayed on retry — never re-applied
        # (same contract as the head's lease/create_actor corr dedup).
        header["corr"] = os.urandom(8).hex()
        # Per-attempt reply deadline: a lost push or dropped reply used to
        # hang until actor-liveness polling noticed; now each attempt is
        # bounded and re-issues with jittered backoff while the actor
        # stays ALIVE (long-running methods keep re-arming — the deadline
        # bounds silence detection, not method runtime).
        attempt_s = float(rt_config.rpc_deadline_s)
        rearm = Backoff(base=0.05, cap=2.0)
        sent_epoch = None
        attempt = 0
        while True:
            try:
                # One atomic critical section for connection resolution AND
                # sequence assignment: dispatch tasks are created in
                # submission order and asyncio.Lock wakes waiters FIFO, so
                # seq order == submission order. (Resolving the connection
                # outside the lock let every coroutine resuming from
                # get_peer re-run the `new connection => seq = 0` reset,
                # clobbering sequence numbers already handed out and
                # reordering actor calls under load.)
                async with ch.lock:
                    conn = await self._actor_conn(ch)
                    if sent_epoch != ch.epoch:
                        # First attempt on this ordering domain: take a
                        # seq. A timeout-retry on the SAME connection
                        # re-sends the SAME (caller, seq, corr) so the
                        # server's in-order admission and dedup both see
                        # one logical call.
                        ch.seq += 1
                        header["seq"] = ch.seq
                        # The ordering domain is (caller, connection
                        # epoch): a reconnect starts a fresh contiguous
                        # seq stream and the server must not mix it with
                        # the old stream's cursor.
                        header["caller"] = (
                            f"{self.worker_id.hex()}:{ch.epoch}"
                        )
                        sent_epoch = ch.epoch
                max_msg = getattr(conn, "max_msg", None)
                if (
                    max_msg is not None
                    and sum(len(f) for f in frames) + 4096 > max_msg
                ):
                    # Oversized for the ring: this call rides TCP. Server-side
                    # seq admission keeps ordering across the two transports.
                    conn = await self.get_peer(ch.addr)
                fl = flight.ENABLED
                if fl:
                    fl_t0 = time.monotonic()
                    if t_enq is not None:
                        # Actor queue time: channel resolution + creation
                        # wait before the first wire attempt.
                        taskpath.record_phase(
                            "queued", header["tid"], t_enq, fl_t0,
                            fn=header.get("method", ""),
                            outcome="actor-pending", phase="lease-wait",
                        )
                        t_enq = None
                if faultpoints.ACTIVE:
                    # drop: the push never reaches the actor worker — the
                    # reply deadline below fires and the corr-tagged retry
                    # re-delivers exactly once.
                    if await faultpoints.async_fire(
                        "worker.actor.push", err=protocol.ConnectionLost
                    ) == "drop":
                        raise asyncio.TimeoutError()
                h, rframes = await asyncio.wait_for(
                    self._call_with_tcp_fallback(
                        conn, ch.addr, "push_actor_task", header, frames
                    ),
                    attempt_s,
                )
                if fl:
                    t_now = time.monotonic()
                    flight.record("worker.actor.push", header["corr"],
                                  "worker", fl_t0, t_now, 0, "ok")
                    taskpath.record_phase(
                        "push", header["tid"], fl_t0, t_now,
                        fn=header.get("method", ""),
                    )
                self._handle_task_reply(header, h, rframes)
                return
            except asyncio.TimeoutError:
                if fl:
                    flight.record("worker.actor.push", header["corr"],
                                  "worker", fl_t0, time.monotonic(), 0,
                                  "timeout")
                # No reply inside the deadline: the request or its reply
                # was lost, or the method is still running. Either way a
                # re-issue is safe (receiver-side corr dedup attaches to
                # the in-flight execution or replays the finished reply),
                # so keep re-arming while the actor is ALIVE — liveness,
                # not a retry count, bounds this (long methods are legal).
                alive = await self._await_actor_alive(ch)
                if not alive:
                    self._fail_task(
                        header,
                        exc.ActorDiedError(
                            header["aid"], ch.death_reason or "died"
                        ),
                    )
                    return
                await asyncio.sleep(rearm.next_delay())
            except (protocol.ConnectionLost, ConnectionRefusedError, OSError):
                ch.conn = None
                alive = await self._await_actor_alive(ch)
                if not alive:
                    self._fail_task(
                        header,
                        exc.ActorDiedError(header["aid"], ch.death_reason or "died"),
                    )
                    return
                if attempt >= retries:
                    self._fail_task(
                        header,
                        exc.ActorUnavailableError(
                            f"actor {header['aid'][:8]} restarted; call was lost "
                            f"(set max_task_retries to resubmit)"
                        ),
                    )
                    return
                attempt += 1
            except protocol.RpcError as e:
                msg = str(e)
                if "ActorMissing" in msg:
                    # Actor no longer hosted there: consult the head for its
                    # fate (restarting elsewhere vs. dead).
                    ch.conn = None
                    alive = await self._await_actor_alive(ch)
                    if not alive:
                        self._fail_task(
                            header,
                            exc.ActorDiedError(
                                header["aid"], ch.death_reason or "actor died"
                            ),
                        )
                        return
                    if attempt >= retries:
                        self._fail_task(
                            header,
                            exc.ActorUnavailableError(
                                f"actor {header['aid'][:8]} restarted; call lost"
                            ),
                        )
                        return
                    attempt += 1
                    continue
                if msg.startswith("TaskError:"):
                    self._fail_task(header, exc.TaskError(msg))
                else:
                    self._fail_task(header, exc.RayTpuError(msg))
                return

    async def _actor_conn(self, ch: _ActorChannel) -> protocol.Connection:
        if ch.dead:
            raise exc.ActorDiedError(ch.actor_id, ch.death_reason)
        if ch.conn is not None and not ch.conn._closed:
            return ch.conn
        if ch.addr is None:
            if not await self._await_actor_alive(ch):
                raise exc.ActorDiedError(ch.actor_id, ch.death_reason)
        # One transport per ordering epoch: the ring (when available) or TCP,
        # never a mix — actor ordering rides the transport's FIFO.
        ring = await self.get_ring(ch.addr)
        ch.conn = ring if ring is not None else await self.get_peer(ch.addr)
        # New connection = new ordering domain for this caller. Callers hold
        # ch.lock across this reset and their own seq assignment.
        ch.seq = 0
        ch.epoch += 1
        return ch.conn

    async def _await_actor_alive(self, ch: _ActorChannel, timeout=60.0) -> bool:
        deadline = time.monotonic() + timeout
        pc = self._actor_creating.get(ch.actor_id)
        if pc is not None:
            # Deferred creation enqueued HERE hasn't reached the head yet:
            # wait for the batch reply (which primes ch.addr / ch.dead)
            # instead of polling a head that can't know the actor.
            while pc.fut is None and not pc.event.is_set():
                if time.monotonic() >= deadline:
                    return False
                await asyncio.sleep(0.001)  # drain callback races us
            if pc.fut is not None and not pc.event.is_set():
                try:
                    # Bounded, not the full deadline: the batch reply is a
                    # gather barrier at the head, so one batchmate stuck in
                    # scheduling (30s unschedulable wait) would hold THIS
                    # actor's already-granted address hostage. The handler
                    # registers each item before scheduling it, so after a
                    # short wait the head poll below can answer for this
                    # actor while the barrier is still up.
                    await asyncio.wait_for(
                        asyncio.shield(pc.fut),
                        min(1.0, max(deadline - time.monotonic(), 0.001)),
                    )
                except asyncio.TimeoutError:
                    pass
            if ch.dead:
                return False
            if ch.addr is not None:
                return True
        # Grace for not-found: a handle can cross a process boundary
        # moments before its deferred creation lands at the head; genuine
        # post-mortem queries still fail fast (dead actors keep a DEAD
        # record — only never-registered ids hit this path).
        grace = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            h, _ = await self._head_call(
                "get_actor", {"actor_id": ch.actor_id}
            )
            if not h.get("found"):
                if (
                    ch.actor_id in self._actor_creating
                    or time.monotonic() < grace
                ):
                    await asyncio.sleep(0.05)
                    continue
                ch.dead = True
                ch.death_reason = "unknown actor"
                return False
            info = h["actor"]
            if info["state"] == "ALIVE":
                ch.addr = tuple(info["addr"])
                return True
            if info["state"] == "DEAD":
                ch.dead = True
                ch.death_reason = info.get("death_reason", "actor died")
                return False
            await asyncio.sleep(0.05)
        return False

    def kill_actor(self, actor_id_hex: str, no_restart: bool = True):
        # A deferred creation must land before the kill or the head would
        # see an unknown actor (and the creation would then leak it).
        self.ensure_actor_created(actor_id_hex)
        self.run_sync(
            self._head_call(
                "kill_actor",
                {"actor_id": actor_id_hex, "no_restart": no_restart},
            )
        )

    # -------------------------------------------------------------- execution

    async def _handle_rpc(self, method, header, frames, conn):
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise protocol.RpcError(f"unknown worker rpc {method}")
        return await fn(header, frames, conn)

    async def rpc_ping(self, h, frames, conn):
        return {"t": time.time()}, []

    async def rpc_pubsub(self, h, frames, conn):
        for cb in self.pubsub_handlers.get(h["channel"], []):
            try:
                cb(h.get("data"), frames)
            except Exception:
                logger.exception("pubsub handler failed")
        return {}, []

    async def rpc_pull_object(self, h, frames, conn):
        """Serve an object we own (blocks until ready — long-poll pull).
        ``direct`` pulls target a non-owner holding a copy (e.g. this worker
        spilled it to its local disk): serve from the head's directory meta
        without waiting on ownership."""
        hex_ = h["oid"]
        entry = self.memory_store.get(hex_)
        if entry is None and h.get("direct"):
            hh, _ = await self._head_call("object_lookup", {"oid": hex_})
            if hh.get("found"):
                entry = ("shm", hh["meta"])
        elif entry is None:
            entry = await self._wait_local(hex_, None)
        if entry is None:
            raise protocol.RpcError(f"object {hex_} unknown to owner")
        kind = entry[0]
        if kind == "mem":
            return {"kind": "mem"}, list(entry[1])
        if kind == "shm":
            if h.get("inline"):
                frames = self.shm.get_frames(hex_, entry[1])
                if frames is None:
                    # Possibly spilled by another process since we recorded
                    # the meta: the head has the authoritative copy.
                    hh, _ = await self._head_call(
                        "object_lookup", {"oid": hex_}
                    )
                    if hh.get("found"):
                        self.memory_store[hex_] = entry = ("shm", hh["meta"])
                        frames = self.shm.get_frames(hex_, hh["meta"])
                if frames is None:
                    raise protocol.RpcError(f"object {hex_} lost at owner")
                return {"kind": "mem"}, [bytes(f) for f in frames]
            return {"kind": "shm", "meta": entry[1]}, []
        if kind == "dev":
            # Metadata only: the puller re-issues a pull_device_shards
            # for the bytes (keeps this long-poll verb payload-free).
            return {"kind": "dev", "spec": entry[1]}, []
        sobj = self.ctx.serialize(entry[1])
        return {"kind": "err"}, sobj.to_frames()

    async def rpc_contains_object(self, h, frames, conn):
        return {"ready": h["oid"] in self.memory_store}, []

    async def rpc_contains_object_batch(self, h, frames, conn):
        """Readiness flags for a whole oid batch (wait()'s remote poller:
        one RPC per owner per cycle instead of one per ref)."""
        store = self.memory_store
        return {"ready": [oid in store for oid in h["oids"]]}, []

    async def rpc_pull_object_batch(self, h, frames, conn):
        """Serve a batch of objects we own over ONE reply with multi-object
        frames (owner-coalesced pulls: a reader resolving N of our objects
        pays one round-trip, not N). Blocks until every requested object is
        ready — the caller's multi-ref get() waits for all of them anyway.
        Per-oid layout mirrors rpc_pull_object: shm objects return their
        meta (the reader maps the segment; ``inline`` forces bytes), mem
        objects return frames, error entries return the pickled exception."""
        oids = h["oids"]
        inline = h.get("inline")

        async def entry_for(hex_):
            entry = self.memory_store.get(hex_)
            if entry is None:
                entry = await self._wait_local(hex_, None)
            return entry

        entries = await asyncio.gather(*(entry_for(o) for o in oids))
        res = []
        frame_lists: List[List[bytes]] = []
        for hex_, entry in zip(oids, entries):
            if entry is None:
                res.append({"kind": "miss"})
                frame_lists.append([])
                continue
            kind = entry[0]
            if kind == "mem":
                res.append({"kind": "mem"})
                frame_lists.append(list(entry[1]))
            elif kind == "shm":
                if inline:
                    fl = self.shm.get_frames(hex_, entry[1])
                    if fl is None:
                        res.append({"kind": "miss"})
                        frame_lists.append([])
                        continue
                    res.append({"kind": "mem"})
                    frame_lists.append([bytes(f) for f in fl])
                else:
                    res.append({"kind": "shm", "meta": entry[1]})
                    frame_lists.append([])
            elif kind == "dev":
                res.append({"kind": "dev", "spec": entry[1]})
                frame_lists.append([])
            else:  # err
                res.append({"kind": "err"})
                frame_lists.append(self.ctx.serialize(entry[1]).to_frames())
        # The helper's counts ARE the wire contract for per-object frame
        # slicing — one source of truth with the flattened payload.
        counts, flat = protocol.pack_multi_frames(frame_lists)
        for r, n in zip(res, counts):
            r["n"] = n
        return {"res": res}, flat

    async def rpc_pull_device_shards(self, h, frames, conn):
        """Serve a device-plane object we hold: ONE reply carries every
        addressable shard as a host buffer plus its global index (the
        cross-slice/DCN leg — same-slice consumers resolve from their own
        device table and never reach this verb). The device→host copies
        run on an executor thread; a multi-GB staging must not stall the
        event loop serving other pulls."""
        hex_ = h["oid"]
        if faultpoints.ACTIVE:
            if await faultpoints.async_fire(
                    "devstore.shard_pull", protocol.RpcError) == "drop":
                # Shards were available, reply lost: the classic
                # applied-but-unacknowledged partial failure — the
                # consumer's attempt deadline re-arms the pull.
                raise faultpoints.DropReply()
        value = self._device_objects.get(hex_)
        if value is None and hex_ not in self.memory_store:
            # Owner still producing (a consumer raced the put):
            # long-poll like pull_object does.
            await self._wait_local(hex_, None)
            value = self._device_objects.get(hex_)
        if value is None:
            raise protocol.RpcError(f"device object {hex_} unknown to owner")
        spec = None
        store_entry = self.memory_store.get(hex_)
        if store_entry is not None and store_entry[0] == "dev":
            spec = store_entry[1]
        loop = asyncio.get_running_loop()
        shards, shard_frames = await loop.run_in_executor(
            None, devstore.pack_shards, value
        )
        return {"spec": spec, "shards": shards}, shard_frames

    async def rpc_add_borrow(self, h, frames, conn):
        for oid in h.get("oids") or [h["oid"]]:
            rec = self.owned.get(oid)
            if rec is not None:
                rec["borrows"] += 1
        return {}, []

    async def rpc_release_borrow(self, h, frames, conn):
        freed: List[str] = []
        for oid in h.get("oids") or [h["oid"]]:
            rec = self.owned.get(oid)
            if rec is not None:
                rec["borrows"] -= 1
                self._maybe_free(oid, free_sink=freed)
        if freed:
            try:
                self.gcs.notify("object_free", {"oids": freed})
            except protocol.ConnectionLost as e:
                logger.debug("object_free (%d oids) on borrow release "
                             "dropped, head gone: %s", len(freed), e)
        return {}, []

    async def rpc_free_object(self, h, frames, conn):
        self._evict_freed(h["oids"])
        return {}, []

    def _evict_freed(self, oids):
        """Global free fan-out (via GCS pubsub): drop borrowed copies —
        cached inline pulls, pulled shm descriptors, local segment attaches.
        Owned entries are freed by _maybe_free, not here."""
        for oid in oids:
            if oid in self.owned:
                continue
            self.memory_store.pop(oid, None)
            self._device_objects.pop(oid, None)  # cached consumer copies
            if self._shm is not None:
                self._shm.free(oid)

    def _decode_arg_frames(self, header, frames):
        """Argument payload of one push back to
        ``(arg_slots, plain, kwargs, split_vals)``: the skeleton tuple,
        then one deserialize per per-arg section (header ``an`` = frame
        counts — the submit-side split that lets repeated args intern
        per peer)."""
        an = header.get("an")
        if not an:
            arg_slots, plain, kwargs = self.ctx.deserialize_frames(frames)
            return arg_slots, plain, kwargs, ()
        cut = len(frames) - sum(an)
        arg_slots, plain, kwargs = self.ctx.deserialize_frames(frames[:cut])
        sep = []
        for n in an:
            sep.append(self.ctx.deserialize_frames(frames[cut:cut + n]))
            cut += n
        return arg_slots, plain, kwargs, sep

    async def _materialize_args(self, header, frames):
        arg_slots, plain, kwargs, sep = self._decode_arg_frames(
            header, frames
        )
        ref_vals = []
        for rid, owner in header.get("argrefs", []):
            ref = ObjectRef(ObjectID.from_hex(rid), tuple(owner) if owner else None)
            ref_vals.append(ref)
        if ref_vals:
            fetched = await self._get_many(ref_vals, None)
        else:
            fetched = []
        args = []
        for kind, idx in arg_slots:
            if kind == "ref":
                args.append(fetched[idx])
            elif kind == "sv":
                args.append(sep[idx])
            else:
                args.append(plain[idx])
        return args, kwargs

    def _pressure_killer_loop(self):
        """Pressure-based task killing (reference behavior:
        ``src/ray/raylet/worker_killing_policy_group_by_owner.h`` driven by
        the memory monitor): while the node is over its memory threshold,
        pick the owner with the most running killable tasks, kill that
        group's NEWEST task (least progress lost), and let the owner's
        retry land elsewhere via the code="oom" node-avoid path. Killable
        = subprocess-backed (runtime-env executor) tasks — killing the
        child actually returns its memory; in-process thread tasks cannot
        be killed and stay guarded by admission rejection + spilling."""
        # Jittered poll: 1s ticks while pressure persists (kills stay
        # responsive), decaying to 4s when the node is calm so N workers'
        # monitors don't sample /proc in lockstep.
        poll = Backoff(base=1.0, cap=4.0, jitter=0.25)
        while not self._shutdown:
            poll.sleep()
            try:
                if not self._memory_monitor.is_pressing():
                    continue
                poll.reset()
                # Victims = tasks ACTUALLY executing inside an env child
                # right now (ex.current_task, set under the executor's
                # lock), and only RETRIABLE ones — killing a max_retries=0
                # task trades a survivable pressure spike for a permanent
                # user-visible failure.
                groups: Dict[tuple, list] = {}
                with self._env_exec_lock:
                    for ex in self._env_executors.values():
                        rec = ex.current_task
                        if rec and rec.get("retriable"):
                            groups.setdefault(rec["owner"], []).append(
                                (rec, ex)
                            )
                if not groups:
                    continue
                _owner, recs = max(groups.items(), key=lambda kv: len(kv[1]))
                victim, ex = max(recs, key=lambda r: r[0]["started"])
                if ex.current_task is not victim:
                    continue  # victim finished since the snapshot; a task
                    # that slipped in behind it may be non-retriable —
                    # re-evaluate next tick rather than kill blind
                ex.pressure_killed = True
                logger.warning(
                    "memory pressure (%s): killing task %s of owner %s "
                    "(retriable; owner will resubmit elsewhere)",
                    self._memory_monitor.usage_string(),
                    victim["tid"][:12], victim["owner"],
                )
                ex.close()
            except Exception:
                logger.exception("pressure killer iteration failed")

    def _run_in_env(self, renv: dict, fn, args, kwargs, owner=(),
                    retriable=False):
        """Execute a pip/uv task inside its cached venv subprocess
        (reference: worker-pool-per-runtime-env; here a per-env executor
        child — see runtime_env/executor.py). Runs on the executor thread;
        a cold venv build blocks only tasks of the SAME env (per-key lock),
        and per-task env_vars/working_dir apply inside the child.
        ``owner``/``retriable`` feed the pressure killer's policy."""
        from ray_tpu._private import runtime_env as renv_mod
        from ray_tpu._private.runtime_env import packaging, venv
        from ray_tpu._private.runtime_env.executor import EnvExecutor

        renv_mod.validate(renv)
        hook = renv.get("worker_process_setup_hook")
        if hook:
            # the hook must run in the process that executes the task —
            # the env-executor CHILD, not this parent
            fn = renv_mod.SetupHookTask(hook, fn)
        use_uv = bool(renv.get("uv"))
        packages = list(renv.get("uv") or renv.get("pip") or ())
        entries = []
        if renv.get("py_modules"):
            entries = packaging.fetch_modules(self, renv["py_modules"])
        if packages and (renv.get("conda") or renv.get("image_uri")):
            raise exc.RayTpuError(
                "runtime_env cannot combine pip/uv with conda or "
                "image_uri: the venv packages would be silently ignored "
                "inside the isolated env (install them via the conda "
                "spec or bake them into the image)"
            )
        if renv.get("image_uri"):
            # working_dir is baked into the container argv as a bind
            # mount: it must key the executor cache too.
            ekey = "img-" + renv["image_uri"] + "@" + (
                renv.get("working_dir") or ""
            )
        elif renv.get("conda"):
            from ray_tpu._private.runtime_env import conda as conda_mod

            ekey = conda_mod.conda_env_key(renv["conda"])
        else:
            ekey = venv.env_key(packages, use_uv)
        key = (ekey, tuple(entries))
        with self._env_exec_lock:
            ex = self._env_executors.get(key)
            if ex is not None and not ex.alive():
                ex.close()
                ex = None
                self._env_executors.pop(key, None)
            key_lock = self._env_exec_keylocks.setdefault(
                key, threading.Lock()
            )
        if ex is None:
            # Build under the PER-KEY lock: a minutes-long pip install of
            # one env must not stall tasks whose env is already built.
            with key_lock:
                with self._env_exec_lock:
                    ex = self._env_executors.get(key)
                if ex is None or not ex.alive():
                    if renv.get("image_uri"):
                        from ray_tpu._private.runtime_env import (
                            conda as conda_mod,
                        )
                        from ray_tpu._private.runtime_env import (
                            executor as exec_mod,
                        )

                        argv = conda_mod.container_argv(
                            renv["image_uri"], exec_mod._CHILD_SRC,
                            path_entries=entries,
                            working_dir=renv.get("working_dir"),
                        )
                        ex = EnvExecutor(
                            "container", path_entries=entries, argv=argv,
                            inherit_parent_site=False,
                        )
                    elif renv.get("conda"):
                        from ray_tpu._private.runtime_env import (
                            conda as conda_mod,
                        )

                        python = conda_mod.ensure_conda_env(renv["conda"])
                        # The env is isolated (no host-site fallback);
                        # cloudpickle — the one package the child loop
                        # needs before user code — is seeded into the env
                        # at creation (conda.py _seed_cloudpickle).
                        ex = EnvExecutor(
                            python, path_entries=entries,
                            inherit_parent_site=False,
                        )
                    else:
                        python = venv.ensure_venv(packages, use_uv=use_uv)
                        ex = EnvExecutor(python, path_entries=entries)
                    with self._env_exec_lock:
                        self._env_executors[key] = ex
        # task_info feeds the pressure killer: only the task ACTUALLY
        # executing inside the child (published under the executor's lock)
        # is a victim candidate, never one queued behind it (reference:
        # worker_killing_policy_group_by_owner.h operates on running
        # workers).
        tid = getattr(self.current_task_id, "value", None)
        task_info = {
            "tid": tid.hex() if tid is not None else "",
            "owner": tuple(owner or ()),
            "started": time.monotonic(),
            "retriable": bool(retriable),
        }
        try:
            ok, result = ex.run(
                fn, args, kwargs,
                env_vars=renv.get("env_vars"),
                cwd=renv.get("working_dir"),
                task_info=task_info,
            )
        except RuntimeError as e:
            with self._env_exec_lock:
                if self._env_executors.get(key) is ex:
                    self._env_executors.pop(key, None)
            ex.close()
            if getattr(ex, "pressure_killed", False):
                # Retriable with node-avoid: the owner backs off this node
                # and resubmits elsewhere (same path as admission OOM).
                # Tasks queued behind the killed one land here too — they
                # were headed for a pressured node either way.
                raise exc.OutOfMemoryError(
                    f"task killed under memory pressure on node "
                    f"{self.node_id[:8]} ({self._memory_monitor.usage_string()})"
                )
            raise exc.WorkerCrashedError(f"runtime-env executor: {e}")
        if ok:
            return True, result
        err_repr, tb = result
        return False, (exc.TaskError(err_repr, tb), tb)
    # Serializes tasks that use working_dir: cwd is process-global, so two
    # concurrent chdir'ing tasks would corrupt each other's view (and the
    # restore). Tasks without working_dir never touch cwd and skip the lock.
    _cwd_lock = threading.Lock()

    def _run_setup_hook(self, renv: dict):
        """worker_process_setup_hook (reference:
        ``_private/runtime_env/setup_hook.py``): run ONCE per worker
        process before the first task using the env executes. Failures
        propagate — a task must not run half-initialized. Runs AFTER the
        rest of the env (env_vars/py_modules/working_dir) is in place so
        hooks may depend on it."""
        hook = (renv or {}).get("worker_process_setup_hook")
        if not hook:
            return
        from ray_tpu._private import runtime_env as renv_mod

        renv_mod.run_setup_hook_once(hook)

    def _apply_runtime_env(self, renv: dict):
        """Per-task environment (reference: _private/runtime_env/ plugins).
        Applied on the executor thread: env_vars, working_dir (cwd is
        process-global, so working_dir tasks serialize on _cwd_lock),
        py_modules (content-addressed fetch + sys.path). pip/uv route the
        EXECUTION into a venv subprocess (see _run_in_env); unknown plugins
        raise — a task must not silently run without the environment it
        asked for."""
        from ray_tpu._private import runtime_env as renv_mod

        renv = renv or {}
        renv_mod.validate(renv)
        inserted = []
        if renv.get("py_modules"):
            from ray_tpu._private.runtime_env import packaging

            entries = packaging.fetch_modules(self, renv["py_modules"])
            import sys as _sys

            for e in reversed(entries):
                # scoped per task (removed in _restore_env): permanent
                # entries would let an older staged version shadow a newer
                # one on re-staged module updates
                if e not in _sys.path:
                    _sys.path.insert(0, e)
                    inserted.append(e)
        envs = renv.get("env_vars") or {}
        old = {}
        for k, v in envs.items():
            old[k] = os.environ.get(k)
            os.environ[k] = str(v)
        cwd = None
        locked = False
        if renv.get("working_dir"):
            self._cwd_lock.acquire()
            locked = True
            cwd = os.getcwd()
            try:
                os.chdir(renv["working_dir"])
            except OSError as e:
                logger.warning("working_dir %r: %s", renv["working_dir"], e)
                cwd = None
        state = {"env": old, "cwd": cwd, "locked": locked,
                 "sys_path": inserted}
        try:
            # after env_vars/py_modules/working_dir: hooks may import
            # staged modules or read the env they were shipped with
            self._run_setup_hook(renv)
        except BaseException:
            self._restore_env(state)
            raise
        return state

    def _restore_env(self, old):
        if old.get("sys_path"):
            import sys as _sys

            for e in old["sys_path"]:
                try:
                    _sys.path.remove(e)
                except ValueError:
                    pass
        if old.get("cwd") is not None:
            try:
                os.chdir(old["cwd"])
            except OSError:
                pass
        if old.get("locked"):
            self._cwd_lock.release()
        for k, v in old.get("env", {}).items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def _record_task_event(self, event: dict):
        """Buffered task events for the state API (reference:
        ``core_worker/task_event_buffer.h`` batching to GcsTaskManager).

        Every event carries the flight-plane join keys: ``cid`` (the task
        id — the same key the ``task.*`` spans and per-task push spans
        record) and, for actor pushes, the RPC ``corr`` id — so
        ``taskpath.task_events_to_merged`` can stitch the event into the
        flight trace with flow links."""
        if not event.get("cid"):
            event["cid"] = event.get("task_id")
        if event.get("corr") is None:
            event.pop("corr", None)
        self._task_events_buf.append(event)

    async def _task_event_flusher(self):
        last_metrics = 0.0
        while not self._shutdown:
            await asyncio.sleep(0.25)
            if self._task_events_buf:
                batch, self._task_events_buf = self._task_events_buf, []
                try:
                    self.gcs.notify("task_events", {"events": batch})
                except protocol.ConnectionLost:
                    return
            now = time.monotonic()
            if now - last_metrics >= 2.0:
                last_metrics = now
                try:
                    from ray_tpu.util.metrics import Gauge, registry

                    if self._shm is not None:
                        # Spill-plane counters ride the same pipeline
                        # (reference: spill stats in the metrics agent).
                        for k, v in self._shm.spill.stats_snapshot().items():
                            Gauge(
                                f"spill_{k}",
                                description="object spill counter",
                            ).set(float(v))
                    if self._push_window and self.leases:
                        # Live adaptive push window per peer slot (max
                        # across a peer's slots: the ramp level a reader
                        # cares about). Bounded cardinality: peers.
                        g = Gauge(
                            "rt_push_window",
                            description="adaptive in-flight push window "
                                        "per peer (tasks)",
                            tag_keys=("peer",),
                        )
                        agg: Dict[str, int] = {}
                        for ls in self.leases.values():
                            for s in ls.slots:
                                if s.pwin is not None:
                                    p = f"{s.addr[0]}:{s.addr[1]}"
                                    agg[p] = max(
                                        agg.get(p, 0), s.pwin.window
                                    )
                        for p, v in agg.items():
                            g.set(float(v), tags={"peer": p})
                    if self._settle_plane is not None:
                        # Settle-plane backlog (round 20): sustained
                        # depth near the handoff bound means reply
                        # settling, not the driver loop, is the choke.
                        Gauge(
                            "rt_settle_queue_depth",
                            description="reply frames queued at the "
                                        "driver settle plane",
                        ).set(float(self._settle_plane.q.depth()))
                    if memtrack.ENABLED:
                        # Object-plane gauges (store bytes by kind, ref
                        # states, arena/graveyard, memory pressure) ride
                        # the same push; the head /metrics rolls them up
                        # per node. On an executor thread: the aggregate
                        # pass is O(owned), and a 1M-task burst must not
                        # stall the core loop for its duration (GIL
                        # interleaving beats a solid loop stall).
                        await asyncio.get_running_loop().run_in_executor(
                            None, memtrack.push_gauges, self
                        )
                    snap = registry().snapshot()
                    if snap:
                        self.gcs.notify("metrics_push", {
                            "worker_id": self.worker_id.hex(),
                            "node_id": self.node_id,
                            "metrics": snap,
                        })
                except protocol.ConnectionLost:
                    return
                except Exception as e:
                    logger.debug("metrics_push failed, dropping sample: %s",
                                 e)

    def _expand_task_header(self, h, frames):
        """Undo submission-plane framing on the executing side: merge the
        pre-framed spec template (frame 0 when header flag ``sp``) back
        into the per-call header — one msgpack decode per DISTINCT spec,
        cached — install a piggybacked function blob (flag ``fb``) into
        the function cache so no kv_get is needed, and re-insert interned
        argument frames (keys ``ai``/``aib``) from the bounded LRU so
        ``deserialize_frames`` sees exactly the bytes the submitter
        framed. Returns the full header plus the (argument) frames.
        Idempotent across the ring fast path and the TCP slow path: a
        second expansion of the same message hits every cache (an ``aib``
        re-store is a no-op overwrite). An evicted ``ai`` digest raises
        the typed ``arg_intern_miss`` error — the pusher answers by
        re-sending the exact bytes."""
        idx = 0
        if h.get("sp"):
            spec = self._spec_cache.get(frames[0])
            merged = {**spec, **h}
            idx = 1
        else:
            merged = dict(h)
        merged.pop("sp", None)
        if merged.pop("fb", None):
            blob = frames[idx]
            idx += 1
            fkey = merged.get("fkey")
            if fkey and fkey not in self.fn_cache:
                try:
                    self._install_function(
                        fkey, cloudpickle.loads(blob), blob
                    )
                except Exception as e:
                    # Fall back to the function table (kv_get) — push-
                    # through is an optimization, never authoritative.
                    logger.debug("piggybacked function %s rejected: %s",
                                 fkey[:8], e)
        ai = merged.pop("ai", None)
        aib = merged.pop("aib", None)
        out = frames[idx:] if idx else frames
        if ai or aib:
            out = self._arg_intern_expand(ai, aib, out)
        return merged, out

    def _arg_intern_expand(self, ai, aib, frames):
        """Rebuild the full argument-frame list: wire frames fill the
        non-interned positions in order, ``ai`` positions come from the
        intern cache (miss => typed error, pusher re-sends), ``aib``
        frames are stored under their digest for the bursts behind this
        push."""
        if ai and faultpoints.ACTIVE:
            # error: force a miss even though the bytes are cached; drop:
            # REALLY evict them first — both funnel into the same typed
            # recovery (re-sent blob, byte-exact round trip).
            forced = False
            try:
                if faultpoints.fire("worker.arg.intern") == "drop":
                    self._arg_intern.purge([d for _p, d in ai])
            except Exception:
                forced = True
            if forced:
                raise protocol.RpcError(
                    "injected interned-arg loss", code="arg_intern_miss"
                )
        ai_map = {p: d for p, d in (ai or ())}
        aib_map = dict(aib) if aib else {}
        total = len(frames) + len(ai_map)
        out = []
        it = iter(frames)
        for pos in range(total):
            digest = ai_map.get(pos)
            if digest is not None:
                blob = self._arg_intern.get(digest)
                if blob is None:
                    raise protocol.RpcError(
                        f"interned arg frame missing at position {pos} "
                        f"(evicted or never covered)",
                        code="arg_intern_miss",
                    )
                out.append(blob)
                continue
            f = next(it)
            store = aib_map.get(pos)
            if store is not None:
                self._arg_intern.put(store, bytes(f))
            out.append(f)
        out.extend(it)
        return out

    async def rpc_push_task(self, h, frames, conn):
        """Execute a normal task (reference: ``CoreWorker::HandlePushTask``
        ``core_worker.cc:3341`` → ExecuteTask), with the round-15 reply
        plane wrapped around the execution core: per-task corr dedup (a
        deadline-re-armed re-push after a dropped coalesced reply frame
        replays the recorded outcome — exactly-once application, the
        ``rpc_push_actor_task`` contract extended to plain tasks) and
        small-result routing into the connection's ReplyWindow (the
        dispatcher sends nothing; the coalesced ``bh`` frame answers this
        correlation id). Big results — any shm-registered return — and
        streaming keep the direct per-task reply path."""
        corr = h.get("corr")
        if corr:
            state, obj = self._apush_begin(corr)
            if state == "replay":
                extras, rframes = obj
                return dict(extras), list(rframes)
            if state == "wait":
                extras, rframes = await asyncio.wrap_future(obj)
                return dict(extras), list(rframes)
        try:
            extras, rframes = await self._push_task_inner(h, frames, conn)
        except BaseException as e:
            # Failed deliveries are retried for real (only successes
            # replay); a DropReply injection lands here too — its retry
            # re-executes, same as the pre-corr contract.
            self._apush_fail(corr, e)
            raise
        self._apush_done(corr, extras, rframes)
        if (
            self._reply_batching
            and isinstance(extras, dict)
            and "rets" in extras
            and all(
                not (isinstance(r, dict) and r.get("kind") == "shm")
                for r in extras["rets"]
            )
        ):
            self._reply_window(conn).add(
                {"i": h["i"], **extras}, rframes, tag=self._window_tag(h)
            )
            return protocol.REPLY_HANDLED, []
        return extras, rframes

    async def _push_task_inner(self, h, frames, conn):
        if self.node_standby:
            # Work arriving means the head activated this node: a later
            # re-registration (blip, head restart) must not claim standby.
            self.node_standby = False
        fl = flight.ENABLED
        if fl:
            fl_srv0 = time.monotonic()
            fb_rode = "fb" in h
            f_cached = h.get("fkey") in self.fn_cache
        if "sp" in h or "fb" in h or "ai" in h or "aib" in h:
            h, frames = self._expand_task_header(h, frames)
        if self._memory_monitor.is_pressing():
            # Reject at admission so this node survives; the owner retries
            # (reference: worker-killing policies under the memory monitor).
            raise protocol.RpcError(
                f"node {self.node_id[:8]} over memory threshold "
                f"({self._memory_monitor.usage_string()})",
                code="oom",
            )
        if fl:
            fl_name = h.get("name") or h.get("fkey", "")[:10]
            t = time.monotonic()
        fn = await self._load_function(h["fkey"])
        if fl:
            # fn-push vs kv_get: the phase the submission-plane
            # push-through exists to eliminate.
            fn_out = (
                "push-through" if fb_rode
                else ("cached" if f_cached else "kv_get")
            )
            now = time.monotonic()
            taskpath.record_phase(
                "fn_load", h["tid"], t, now, fn=fl_name, outcome=fn_out,
                phase="kv-get" if fn_out == "kv_get" else "fn-push",
            )
            t = now
        args, kwargs = await self._materialize_args(h, frames)
        if fl:
            taskpath.record_phase(
                "arg_pull", h["tid"], t, time.monotonic(), fn=fl_name,
                nbytes=sum(len(f) for f in frames), phase="arg-pull",
            )
        if h.get("nret") == -1:
            return await self._execute_streaming_task(h, fn, args, kwargs, conn)
        loop = asyncio.get_running_loop()

        def run():
            from ray_tpu.util.tracing import tracing_helper

            renv = h.get("renv") or {}
            tid = TaskID.from_hex(h["tid"])
            self.current_task_id.value = tid
            self.current_actor_id.value = None
            self.put_counter.value = 0
            # Key PRESENCE routes, not truthiness: {"pip": []} explicitly
            # asks for venv isolation (a subprocess executor) even with
            # nothing to install.
            if any(k in renv for k in ("pip", "uv", "conda", "image_uri")):
                # Whole env (incl. env_vars/working_dir/py_modules) applies
                # inside the venv/conda/container child — the parent
                # process must stay unpolluted.
                try:
                    with tracing_helper.span(
                        f"task::{h.get('name', 'task')}", h.get("trace"),
                        {"task_id": h["tid"], "node_id": self.node_id},
                    ):
                        return self._run_in_env(
                            renv, fn, args, kwargs,
                            owner=tuple(h.get("owner") or ()),
                            retriable=h.get("retries", 0) > 0,
                        )
                except Exception as e:
                    return False, (e, traceback.format_exc())
            try:
                old = self._apply_runtime_env(renv)
            except Exception as e:
                return False, (e, traceback.format_exc())
            try:
                with tracing_helper.span(
                    f"task::{h.get('name', 'task')}", h.get("trace"),
                    {"task_id": h["tid"], "node_id": self.node_id},
                ):
                    return True, fn(*args, **kwargs)
            except Exception as e:
                return False, (e, traceback.format_exc())
            finally:
                self._restore_env(old)

        if faultpoints.ACTIVE:
            # crash = this worker process dies mid-dispatch (after the
            # lease was consumed, before any reply) — the hard partial
            # failure the chaos matrix exercises.
            await faultpoints.async_fire("worker.task.exec")
        t0 = time.time()
        if fl:
            tm = time.monotonic()
        ok, result = await loop.run_in_executor(self.task_executor, run)
        self._stats["tasks_executed"] += 1
        if fl:
            taskpath.record_phase(
                "exec", h["tid"], tm, time.monotonic(), fn=fl_name,
                outcome="ok" if ok else "error", phase="exec",
            )
        self._record_task_event({
            "task_id": h["tid"], "name": h.get("name") or h["fkey"],
            "type": "NORMAL_TASK",
            "state": "FINISHED" if ok else "FAILED",
            "start_time": t0, "end_time": time.time(),
            "node_id": self.node_id,
        })
        if not ok and isinstance(result[0], exc.OutOfMemoryError):
            # Pressure-killed mid-run: surface as the SAME retriable
            # code="oom" rejection the admission path uses — the owner
            # backs off this node and resubmits elsewhere.
            raise protocol.RpcError(str(result[0]), code="oom")
        if not fl:
            return await self._package_result(h, ok, result)
        tm = time.monotonic()
        out = await self._package_result(h, ok, result)
        now = time.monotonic()
        taskpath.record_phase(
            "result", h["tid"], tm, now, fn=fl_name, phase="result-push",
        )
        # Serve envelope: arrival → reply ready; the driver derives
        # reply-ack (wire both ways) as its push span minus this.
        flight.record("task.serve", h["tid"], "task", fl_srv0, now)
        return out

    async def _execute_streaming_task(self, h, fn, args, kwargs, conn):
        """Run a generator task, pushing each yielded item to the owner as
        it is produced (reference: streaming generator returns — the owner
        can consume item i while item i+1 is still being computed). The
        bounded queue backpressures the producer against a slow consumer
        path; items ride oneway "stream_item" messages on the same
        connection, so they arrive before the final count reply."""
        import inspect

        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue(maxsize=8)
        tid = TaskID.from_hex(h["tid"])
        t0 = time.time()

        abandon = threading.Event()

        def qput(entry) -> bool:
            """Blocking put that stays abandonable: the pump (or its
            teardown) sets `abandon` and this producer thread unblocks
            within a second even if the event loop never drains the queue
            again (e.g. the pump task was cancelled)."""
            # Checked up front: once the pump abandons the stream it drains
            # the queue, so puts would keep succeeding and an infinite
            # generator would never stop producing.
            if abandon.is_set() or loop.is_closed():
                return False
            try:
                f = asyncio.run_coroutine_threadsafe(q.put(entry), loop)
            except RuntimeError:
                return False  # loop shut down under us
            # Never cancel the put: cancellation can race its completion and
            # a retry would enqueue the entry twice. Keep waiting on the SAME
            # future, bailing out between waits once abandoned (the dangling
            # put then lands, at worst, in a queue nobody reads again).
            while True:
                try:
                    f.result(timeout=1.0)
                    return True
                except SyncTimeoutError:
                    if abandon.is_set() or loop.is_closed():
                        return False
                except (SyncCancelledError, RuntimeError):
                    return False  # loop shut down under us

        def produce():
            old = self._apply_runtime_env(h.get("renv"))
            self.current_task_id.value = tid
            self.current_actor_id.value = None
            self.put_counter.value = 0
            try:
                gen = fn(*args, **kwargs)
                if not inspect.isgenerator(gen):
                    raise TypeError(
                        "num_returns='streaming' requires a generator "
                        f"function; {h.get('name', 'task')} returned "
                        f"{type(gen).__name__}"
                    )
                for item in gen:
                    if not qput(("item", item)):
                        return
                qput(("end", None))
            except Exception as e:
                tb = traceback.format_exc()
                qput(("err", (e, tb)))
            finally:
                self._restore_env(old)

        prod = loop.run_in_executor(self.task_executor, produce)
        credits = self._stream_credits[h["tid"]] = {
            "consumed": 0, "event": asyncio.Event(),
        }
        idx = 0
        failed = False
        sentinel = False  # saw the producer's final "end"/"err" entry
        try:
            while True:
                kind, payload = await q.get()
                if kind == "item":
                    try:
                        # Owner-side flow control: never run more than WINDOW
                        # items ahead of what the consumer acknowledged — a
                        # fast producer must not fill the owner's memory. A
                        # consumer silent for 10 minutes fails the stream
                        # rather than pinning this executor slot forever.
                        while idx >= credits["consumed"] + self._STREAM_WINDOW:
                            credits["event"].clear()
                            try:
                                await asyncio.wait_for(
                                    credits["event"].wait(), timeout=600
                                )
                            except asyncio.TimeoutError:
                                raise exc.RayTpuError(
                                    "stream consumer stalled >600s; aborting "
                                    "generator task"
                                )
                        await self._send_stream_item(
                            conn, h, tid, idx, payload
                        )
                        idx += 1
                    except Exception as e:
                        # The usual cause is the owner connection closing, so
                        # the error notification itself may fail — it must
                        # not skip the producer unblock below.
                        try:
                            await self._send_stream_error(
                                conn, h, tid, idx,
                                exc.TaskError(
                                    f"stream item send failed: {e!r}"
                                ),
                            )
                        except Exception:
                            pass
                        idx += 1
                        failed = True
                        break
                elif kind == "err":
                    e, tb = payload
                    sentinel = True
                    try:
                        await self._send_stream_error(
                            conn, h, tid, idx,
                            exc.TaskError(repr(e), tb, cause=e),
                        )
                    except Exception:
                        pass
                    idx += 1
                    failed = True
                    break
                else:
                    sentinel = True
                    break
        finally:
            # Runs on every exit — send failure, handler cancellation at
            # teardown, unexpected errors — and must always unblock the
            # producer thread (queue maxsize is small; a stuck producer
            # permanently leaks a task_executor slot).
            self._stream_credits.pop(h["tid"], None)
            if not sentinel:
                abandon.set()  # timed puts in the producer observe this
            try:
                while not sentinel and not prod.done():
                    try:
                        q.get_nowait()
                    except asyncio.QueueEmpty:
                        await asyncio.sleep(0.05)
                await prod
            except BaseException:
                # Re-cancelled during teardown: the abandon event still
                # guarantees the producer exits within its put timeout.
                if not abandon.is_set():
                    abandon.set()
                raise
        self._stats["tasks_executed"] += 1
        self._record_task_event({
            "task_id": h["tid"], "name": h.get("name") or h["fkey"],
            "type": "NORMAL_TASK",
            "state": "FAILED" if failed else "FINISHED",
            "start_time": t0, "end_time": time.time(),
            "node_id": self.node_id,
        })
        return {"stream": 1, "count": idx}, []

    async def _send_stream_item(self, conn, h, tid, idx, value):
        sobj = self.ctx.serialize(value)
        base = {"tid": h["tid"], "idx": idx}
        if sobj.total_bytes() <= INLINE_OBJECT_MAX:
            conn.notify(
                "stream_item", {**base, "kind": "mem"}, sobj.to_frames()
            )
        else:
            oid = ObjectID.for_return(tid, idx).hex()
            meta = self._with_xfer(
                self.shm.put_frames(oid, sobj.to_frames(copy=False))
            )
            await self.gcs.call("object_register", {"oid": oid, "meta": meta})
            conn.notify("stream_item", {**base, "kind": "shm", "meta": meta})

    # Max items a generator may run ahead of its consumer's acknowledgments.
    _STREAM_WINDOW = 16

    async def rpc_stream_credit(self, h, frames, conn):
        """Executor side: the consumer acknowledged items up to `consumed`
        (or abandoned the stream — consumed jumps effectively unbounded so
        the producer drains to completion instead of hanging)."""
        rec = self._stream_credits.get(h["tid"])
        if rec is not None:
            rec["consumed"] = max(rec["consumed"], int(h["consumed"]))
            rec["event"].set()
        return {}, []

    def _send_stream_credit(self, tid_hex: str, consumed: int):
        """Owner side: fire a credit on the stream's producer connection."""
        rec = self._task_streams.get(tid_hex)
        conn = rec.get("conn") if rec else None
        if conn is None:
            return
        try:
            conn.notify(
                "stream_credit", {"tid": tid_hex, "consumed": consumed}
            )
        except Exception as e:
            # Producer gone: nothing left to throttle.
            logger.debug("stream_credit for %s dropped: %s", tid_hex, e)

    def _abandon_stream(self, tid_hex: str, next_index: int):
        """The consumer dropped its generator: free arrived-but-unconsumed
        items, discard future arrivals, and un-throttle the producer so the
        executing task can run to completion."""
        rec = self._task_streams.get(tid_hex)
        if rec is None:
            return
        rec["abandoned"] = True
        tid = TaskID.from_hex(tid_hex)
        for i in range(next_index, rec.get("produced", 0)):
            self._dec_ref_local(ObjectID.for_return(tid, i).hex())
        self._send_stream_credit(tid_hex, 1 << 60)
        if rec.get("count") is not None:
            self._task_streams.pop(tid_hex, None)

    async def _send_stream_error(self, conn, h, tid, idx, err):
        try:
            fr = self.ctx.serialize(err).to_frames()
        except Exception:
            fr = self.ctx.serialize(
                exc.TaskError(f"unserializable stream error: {err!r}")
            ).to_frames()
        conn.notify(
            "stream_item", {"tid": h["tid"], "idx": idx, "kind": "err"}, fr
        )

    def _drop_stream_item(self, h):
        """Discard an unwanted stream item, releasing its shm registration
        (abandoned consumer, or a late arrival after the stream's length was
        finalized)."""
        if h["kind"] == "shm":
            oid = ObjectID.for_return(
                TaskID.from_hex(h["tid"]), h["idx"]
            ).hex()
            try:
                self.gcs.notify("object_free", {"oids": [oid]})
            except Exception as e:
                logger.debug("object_free for dropped stream item %s "
                             "failed: %s", oid, e)

    async def rpc_stream_item(self, h, frames, conn):
        """Owner side: one streamed item landed (stored like a task return;
        an "err" item raises on get, ending consumption with the failure)."""
        rec = self._task_streams.get(h["tid"])
        if rec is not None:
            rec["conn"] = conn  # credit/abandon messages ride this
        if rec is None or rec.get("abandoned"):
            # consumer is gone: discard, and free any shm registration
            self._drop_stream_item(h)
            return {}, []
        count = rec.get("count")
        if count is not None and (
            h["idx"] >= count or h["idx"] == rec.get("failed_idx", -1)
        ):
            # The stream's length is already finalized: a late in-flight item
            # at/after that index — or at the slot where _fail_task stored
            # the failure — must not overwrite the recorded outcome.
            self._drop_stream_item(h)
            return {}, []
        oid = ObjectID.for_return(
            TaskID.from_hex(h["tid"]), h["idx"]
        ).hex()
        if h["kind"] == "mem":
            entry = ("mem", frames)
        elif h["kind"] == "shm":
            entry = ("shm", h["meta"])
        else:
            entry = ("err", self.ctx.deserialize_frames(frames))
        self.memory_store[oid] = entry
        self._register_owned(oid)
        ev = self.store_events.get(oid)
        if ev is not None:
            ev.set()
        rec["produced"] = max(rec.get("produced", 0), h["idx"] + 1)
        sev = rec.get("event")
        if sev is not None:
            sev.set()
        return {}, []

    def _package_result_parts(self, h, ok, result):
        """Sync result packaging. Returns (rets, out_frames, big) where
        ``big`` holds (index, serialized) for values too large to inline —
        their rets entries are placeholders the caller must fill after the
        shm write + head registration."""
        nret = h.get("nret", 1)
        rets: List[Any] = []
        out_frames: List[bytes] = []
        if not ok:
            e, tb = result
            err = exc.TaskError(repr(e), tb, cause=e)
            try:
                sobj = self.ctx.serialize(err)
            except Exception:
                sobj = self.ctx.serialize(exc.TaskError(repr(e), tb))
            fr = sobj.to_frames()
            for _ in range(nret):
                rets.append({"kind": "err", "nframes": len(fr)})
                out_frames.extend(fr)
            return rets, out_frames, []
        values = (
            list(result)
            if nret > 1 and isinstance(result, (tuple, list))
            else [result]
        )
        if nret > 1 and len(values) != nret:
            err = exc.TaskError(
                f"task declared num_returns={nret} but returned {len(values)} values"
            )
            fr = self.ctx.serialize(err).to_frames()
            for _ in range(nret):
                rets.append({"kind": "err", "nframes": len(fr)})
                out_frames.extend(fr)
            return rets, out_frames, []
        big = []
        for i, v in enumerate(values[:nret]):
            # Refs nested in a return value must be pinned exactly like
            # put() pins them (reference: borrow registration on value
            # serialization, reference_counter.h): this worker holds a
            # borrow until the CALLER frees the return object and sends
            # release_borrow back. Without this, a task returning
            # [ray.put(...), ...] frees the pieces the moment its locals
            # are GC'd — the distributed-shuffle map->reduce handoff.
            sobj, nested_refs = collect_refs_during(
                lambda v=v: self.ctx.serialize(v)
            )
            nested = [
                (r.id().hex(), list(r.owner_address or ()))
                for r in nested_refs
            ]
            ret: Dict[str, Any] = {}
            if nested:
                self._add_borrows(nested)
                ret["nested"] = nested
            if sobj.total_bytes() <= INLINE_OBJECT_MAX:
                fr = sobj.to_frames()
                rets.append({**ret, "kind": "mem", "nframes": len(fr)})
                out_frames.extend(fr)
            else:
                # placeholder: filled after shm write (nested carried over)
                rets.append(None)
                big.append((i, sobj, ret))
        return rets, out_frames, big

    async def _package_result(self, h, ok, result):
        rets, out_frames, big = self._package_result_parts(h, ok, result)
        tid = TaskID.from_hex(h["tid"])
        for i, sobj, ret in big:
            oid = ObjectID.for_return(tid, i).hex()
            # written into shm before this call returns: zero-copy safe
            meta = self._with_xfer(
                self.shm.put_frames(oid, sobj.to_frames(copy=False))
            )
            await self.gcs.call("object_register", {"oid": oid, "meta": meta})
            rets[i] = {**ret, "kind": "shm", "meta": meta}
        return {"rets": rets}, out_frames

    # actor hosting ---------------------------------------------------------

    async def rpc_create_actor(self, h, frames, conn):
        """Instantiate an actor here (pushed by the head's actor scheduler)."""
        if self.node_standby:
            # Placement arriving means the head activated this node.
            self.node_standby = False
        spec = cloudpickle.loads(frames[0])
        cls = await self._load_function(spec["class_key"])
        real_cls = getattr(cls, "__rt_wrapped_cls__", cls)
        args, kwargs = await self._materialize_args(
            {"argrefs": spec.get("argrefs", [])}, frames[1:]
        )
        loop = asyncio.get_running_loop()

        def construct():
            renv = spec.get("renv") or {}
            if any(k in renv for k in ("pip", "uv", "conda", "image_uri")):
                return False, (
                    exc.RayTpuError(
                        "actors with pip/uv/conda/image_uri runtime envs "
                        "are not supported: "
                        "the actor would live outside the TPU-owning worker "
                        "process (use py_modules, or run a task instead)"
                    ),
                    "",
                )
            try:
                old = self._apply_runtime_env(renv)
            except Exception as e:
                return False, (e, traceback.format_exc())
            self.current_actor_id.value = h["actor_id"]
            try:
                return True, real_cls(*args, **kwargs)
            except Exception as e:
                return False, (e, traceback.format_exc())
            finally:
                self._restore_env(old)

        ok, result = await loop.run_in_executor(self.task_executor, construct)
        if not ok:
            e, tb = result
            raise protocol.RpcError(f"TaskError: actor __init__ failed: {e!r}\n{tb}")
        is_async = any(
            asyncio.iscoroutinefunction(getattr(real_cls, m, None))
            for m in dir(real_cls)
            if not m.startswith("_")
        )
        inst = _ActorInstance(
            h["actor_id"], result, spec.get("max_concurrency", 1) or 1,
            is_async,
            concurrency_groups=spec.get("concurrency_groups"),
        )
        # Re-reported to a restarted head so live actors survive head loss
        # (see _reconnect_gcs / rpc_register_node hosted_actors).
        inst.public_meta = dict(h.get("meta") or {})
        self.hosted_actors[h["actor_id"]] = inst
        return {}, []

    async def rpc_kill_actor(self, h, frames, conn):
        inst = self.hosted_actors.pop(h["actor_id"], None)
        if inst is not None:
            inst.exiting = True
            inst.pool.shutdown(wait=False, cancel_futures=True)
            for pool in inst.groups.values():
                pool.shutdown(wait=False, cancel_futures=True)
        return {}, []

    # Correlation-id dedup for actor-call pushes. The sender retries a
    # push whose reply missed its deadline; the retry re-delivers the same
    # (corr, caller, seq). In-order admission routes such duplicates off
    # the ring fast path (seq < cursor), so they always land in
    # rpc_push_actor_task — which must replay the original outcome, never
    # run the method twice.

    def _apush_begin(self, corr):
        """Dedup gate. Returns ("mine", None) for a first delivery (caller
        executes, then _apush_done/_apush_fail), ("replay", (extras,
        frames)) for a duplicate of a completed call, or ("wait", fut) for
        a duplicate of a still-executing call (a SyncFuture resolved by
        the executing path). Thread-safe: the ring fast paths call this
        from pump/executor threads."""
        if not corr:
            return ("mine", None)
        with self._apush_lock:
            e = self._apush_replies.get(corr)
            if e is None:
                self._apush_replies[corr] = _APUSH_WIP
                return ("mine", None)
            if e is _APUSH_WIP:
                fut = SyncFuture()
                self._apush_replies[corr] = fut
                return ("wait", fut)
            if isinstance(e, SyncFuture):
                return ("wait", e)
            return ("replay", (e[1], e[2]))

    def _apush_trim_locked(self):
        """Evict completed entries (oldest first) — but never one younger
        than the sender's retry horizon (its duplicate may still be in
        flight; evicting it would re-execute a non-idempotent method),
        and never an in-flight marker (skipped by rotation, so one
        long-running call cannot wedge eviction behind it and grow the
        cache without bound). Beyond the hard cap, age no longer
        protects: memory wins over an already-pathological retry.
        Called every 32nd completion (plus at the hard cap) — per-call
        it was a measurable slice of the task hot path once plain tasks
        joined the corr plane. Hard-cap evictions drain a full
        ``_APUSH_CACHE`` band in one pass: evicting a single entry would
        leave the cache AT the cap, re-firing the trim on every
        subsequent completion (the equilibrium that put this function at
        ~1 call/task in the drain-thread profile)."""
        horizon = self._apush_horizon_s
        hard_lo = 7 * self._APUSH_CACHE
        now = time.monotonic()
        scanned = 0
        while (len(self._apush_replies) > self._APUSH_CACHE
               and scanned < 512):
            k = next(iter(self._apush_replies))
            v = self._apush_replies[k]
            scanned += 1
            if v is _APUSH_WIP or isinstance(v, SyncFuture):
                self._apush_replies.move_to_end(k)
                continue
            if (now - v[0] < horizon
                    and len(self._apush_replies) < hard_lo):
                break
            self._apush_replies.pop(k, None)

    def _apush_done(self, corr, extras, frames):
        """Cache a successful reply and wake any attached retry."""
        if not corr:
            return
        with self._apush_lock:
            e = self._apush_replies.get(corr)
            # Stored by reference: every caller hands a freshly built
            # frame list it never mutates, and replay sites copy at send.
            self._apush_replies[corr] = (time.monotonic(), extras, frames)
            self._apush_done_n += 1
            if (self._apush_done_n & 31) == 0 or (
                len(self._apush_replies) >= 8 * self._APUSH_CACHE
            ):
                self._apush_trim_locked()
        if isinstance(e, SyncFuture) and not e.done():
            e.set_result((extras, frames))

    def _apush_begin_many(self, corrs):
        """One-lock batch of :meth:`_apush_begin` for a chunk's corr ids
        (``None``/empty entries yield ``("mine", None)`` untouched) —
        per-task begin/done lock traffic was a measured slice of the
        drain profile once plain tasks joined the corr plane."""
        out = []
        with self._apush_lock:
            replies = self._apush_replies
            for corr in corrs:
                if not corr:
                    out.append(("mine", None))
                    continue
                e = replies.get(corr)
                if e is None:
                    replies[corr] = _APUSH_WIP
                    out.append(("mine", None))
                elif e is _APUSH_WIP:
                    fut = SyncFuture()
                    replies[corr] = fut
                    out.append(("wait", fut))
                elif isinstance(e, SyncFuture):
                    out.append(("wait", e))
                else:
                    out.append(("replay", (e[1], e[2])))
        return out

    def _apush_done_many(self, entries):
        """One-lock batch of :meth:`_apush_done`: ``entries`` =
        [(corr, extras, frames)]. Attached retries wake outside the
        lock; the trim check amortizes over the whole batch."""
        if not entries:
            return
        wake = []
        now = time.monotonic()
        with self._apush_lock:
            replies = self._apush_replies
            for corr, extras, frames in entries:
                e = replies.get(corr)
                replies[corr] = (now, extras, frames)
                if isinstance(e, SyncFuture):
                    wake.append((e, extras, frames))
            self._apush_done_n += len(entries)
            if (self._apush_done_n & 31) < len(entries) or (
                len(replies) >= 8 * self._APUSH_CACHE
            ):
                self._apush_trim_locked()
        for fut, extras, frames in wake:
            if not fut.done():
                fut.set_result((extras, frames))

    def _apush_fail(self, corr, err):
        """A failed delivery is retried for real (only successes replay);
        attached retries observe the failure."""
        if not corr:
            return
        with self._apush_lock:
            e = self._apush_replies.pop(corr, None)
        if isinstance(e, SyncFuture) and not e.done():
            e.set_exception(err)

    async def _admit_in_order(self, inst: _ActorInstance, caller: str, seq: int):
        if seq <= 0:
            return
        with inst.seq_lock:
            nxt = inst.next_seq.setdefault(caller, 1)
            if seq <= nxt:
                return
            waiters = inst.buffered.setdefault(caller, {})
            ev = asyncio.Event()
            waiters[seq] = ev
        await ev.wait()

    def _advance_seq(self, inst: _ActorInstance, caller: str, seq: int):
        if seq <= 0:
            return
        with inst.seq_lock:
            if inst.next_seq.get(caller, 1) != seq:
                return
            inst.next_seq[caller] = seq + 1
            ev = inst.buffered.get(caller, {}).pop(seq + 1, None)
        if ev is not None:
            # asyncio.Event.set is loop-affine and the fast path advances
            # from the ring pump thread; call_soon_threadsafe is legal from
            # the loop thread too, so use it unconditionally.
            try:
                self.loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass  # loop closing; waiter is being cancelled anyway

    async def rpc_push_actor_task(self, h, frames, conn):
        """Execute an actor method (reference: direct PushActorTask gRPC +
        ordered TaskReceiver queues ``task_execution/*_queue.h``), with
        correlation-id dedup: a retried delivery (reply dropped or
        deadline-raced) replays the original outcome or attaches to the
        in-flight execution — exactly-once application per corr id."""
        corr = h.get("corr")
        state, obj = self._apush_begin(corr)
        if state == "replay":
            extras, rframes = obj
            return dict(extras), list(rframes)
        if state == "wait":
            extras, rframes = await asyncio.wrap_future(obj)
            return dict(extras), list(rframes)
        try:
            extras, rframes = await self._push_actor_task_inner(
                h, frames, conn
            )
        except BaseException as e:
            self._apush_fail(corr, e)
            raise
        self._apush_done(corr, extras, rframes)
        if (
            self._reply_batching
            and isinstance(extras, dict)
            and "rets" in extras
            and all(
                not (isinstance(r, dict) and r.get("kind") == "shm")
                for r in extras["rets"]
            )
        ):
            # Small actor results coalesce the same way task results do;
            # shm-registered returns keep the direct per-call reply.
            self._reply_window(conn).add(
                {"i": h["i"], **extras}, rframes, tag=self._window_tag(h)
            )
            return protocol.REPLY_HANDLED, []
        return extras, rframes

    async def _push_actor_task_inner(self, h, frames, conn):
        inst = self.hosted_actors.get(h["aid"])
        if inst is None:
            raise protocol.RpcError(f"ActorMissing: actor {h['aid']} not hosted here")
        if inst.exiting:
            raise protocol.RpcError("ActorMissing: actor exiting")
        # Ordered admission per caller BEFORE any fallible work, so a failed
        # call (bad method, lost arg) still advances the sequence and cannot
        # wedge later calls (reference: SequentialActorSubmitQueue semantics).
        caller, seq = h.get("caller", ""), h.get("seq", 0)
        await self._admit_in_order(inst, caller, seq)
        loop = asyncio.get_running_loop()
        ev_start = time.time()
        fl = flight.ENABLED
        if fl:
            tm0 = time.monotonic()
        try:
            if h["method"] == "__rt_apply__":
                # Generic dispatch: run fn(instance, *args) on this actor.
                # Used by compiled graphs to install per-actor exec loops
                # (reference analog: compiled_dag_node.py:185 exec loop tasks
                # submitted onto the DAG's actors).
                def method(fn, *a, **kw):
                    return fn(inst.instance, *a, **kw)
            else:
                method = getattr(inst.instance, h["method"], None)
            if method is None:
                raise protocol.RpcError(
                    f"TaskError: actor has no method '{h['method']}'"
                )
            try:
                cg = inst.resolve_group(method, h)
            except KeyError as e:
                raise protocol.RpcError(
                    f"TaskError: unknown concurrency group {e.args[0]!r} "
                    f"(declared: {sorted(inst.groups)})"
                )
            args, kwargs = await self._materialize_args(h, frames)
            if asyncio.iscoroutinefunction(method):
                # Run on the dedicated async-actor loop, NOT the core loop:
                # a blocking ray_tpu.get() inside the method would otherwise
                # deadlock the whole process. Concurrency is gated by the
                # ASYNC-side semaphore (acquired on that loop) so the fast
                # ring path and this path share one limit; admission order
                # is the FIFO scheduling order onto the async loop, so seq
                # advances at scheduling time.
                async def _run_with_ctx():
                    async with inst.async_sem_for(cg):
                        _async_actor_id.set(h["aid"])
                        _async_task_id.set(h["tid"])
                        return await method(*args, **kwargs)

                afut = asyncio.run_coroutine_threadsafe(
                    _run_with_ctx(), self._get_async_loop()
                )
                self._advance_seq(inst, caller, seq)
                try:
                    result, ok = await asyncio.wrap_future(afut), True
                except (Exception, SystemExit) as e:
                    result, ok = (e, traceback.format_exc()), False
            else:
                def run():
                    tid = TaskID.from_hex(h["tid"])
                    self.current_task_id.value = tid
                    self.current_actor_id.value = h["aid"]
                    self.put_counter.value = 0
                    return method(*args, **kwargs)

                fut = loop.run_in_executor(inst.pool_for(cg), run)
                # Pool admission happened in seq order; later seqs may now queue.
                self._advance_seq(inst, caller, seq)
                try:
                    result, ok = await fut, True
                except (Exception, SystemExit) as e:
                    result, ok = (e, traceback.format_exc()), False
        finally:
            self._advance_seq(inst, caller, seq)
        inst.num_executed += 1
        if fl:
            taskpath.record_phase(
                "exec", h["tid"], tm0, time.monotonic(), fn=h["method"],
                outcome="ok" if ok else "error", phase="exec",
            )
        self._record_task_event({
            "task_id": h["tid"], "name": h["method"], "type": "ACTOR_TASK",
            "actor_id": h["aid"], "corr": h.get("corr"),
            "state": "FINISHED" if ok else "FAILED",
            "start_time": ev_start, "end_time": time.time(),
            "node_id": self.node_id,
        })
        if not ok:
            e, tb = result if isinstance(result, tuple) else (result, "")
            if isinstance(e, SystemExit):
                # exit_actor(): report clean exit to the head
                self.hosted_actors.pop(h["aid"], None)
                self.gcs.notify(
                    "actor_exited",
                    {"actor_id": h["aid"], "clean": True, "reason": "exit_actor"},
                )
                raise protocol.RpcError("ActorMissing: actor exited")
            return await self._package_result(h, False, (e, tb))
        return await self._package_result(h, True, result)

    # ------------------------------------------------------------------ misc

    _async_loop_lock = threading.Lock()

    def _get_async_loop(self) -> asyncio.AbstractEventLoop:
        """Dedicated event loop thread for async actor method bodies
        (reference: per-actor asyncio loops in the Python worker). Keeping
        user coroutines off the core loop means blocking calls inside them
        (get/put/wait) cannot deadlock the process's networking. Called
        from the core loop AND the ring pump thread — locked so two racing
        callers cannot spawn two loops."""
        loop = getattr(self, "_async_actor_loop", None)
        if loop is not None:
            return loop
        with self._async_loop_lock:
            loop = getattr(self, "_async_actor_loop", None)
            if loop is not None:
                return loop
            return self._spawn_async_loop()

    def _spawn_async_loop(self) -> asyncio.AbstractEventLoop:
        ready = threading.Event()
        holder = {}

        def runner():
            l = asyncio.new_event_loop()
            asyncio.set_event_loop(l)
            holder["loop"] = l
            ready.set()
            l.run_forever()

        t = threading.Thread(target=runner, name="rt-async-actors", daemon=True)
        t.start()
        ready.wait(timeout=10)
        self._async_actor_loop = holder["loop"]
        return self._async_actor_loop

    async def rpc_flight_drain(self, h, frames, conn):
        """Hand this process's flight-recorder ring to the head (the
        ``flight_snapshot`` fan-out). The reply carries our wall clock so
        the head can offset-correct our spans onto its own."""
        snap = flight.drain() if h.get("drain", True) else flight.snapshot()
        return {"flight": snap, "enabled": flight.ENABLED}, []

    async def rpc_memstat_drain(self, h, frames, conn):
        """Hand this process's object/memory accounting to the head (the
        ``memory_summary`` fan-out). Disabled plane answers without a
        payload — same contract as tool clients on ``flight_drain``. The
        snapshot pass is O(owned) and runs on an executor thread so an
        operator summary mid-burst never stalls the core loop."""
        if not memtrack.ENABLED:
            return {"enabled": False}, []
        snap = await asyncio.get_running_loop().run_in_executor(
            None, memtrack.local_snapshot, self
        )
        return {"memstat": snap, "enabled": True}, []

    async def rpc_dump_stacks(self, h, frames, conn):
        """All-thread stack dump (reference: py-spy via the reporter agent's
        profile_manager; here native to the worker — util/debug.py)."""
        from ray_tpu.util.debug import dump_local_stacks

        return {"stacks": dump_local_stacks()}, []

    async def rpc_memory_profile(self, h, frames, conn):
        """tracemalloc control on this worker (memray analog)."""
        from ray_tpu.util.debug import memory_profile_local

        return memory_profile_local(
            h.get("action", "snapshot"), h.get("top", 10)
        ), []

    async def rpc_cpu_profile(self, h, frames, conn):
        """Sampling CPU profile (py-spy record analog): the sampler runs
        on an executor thread so the event loop stays live; returns
        collapsed flamegraph stacks."""
        from ray_tpu.util.debug import sample_cpu_profile

        loop = asyncio.get_running_loop()
        folded = await loop.run_in_executor(
            None,
            lambda: sample_cpu_profile(
                float(h.get("duration_s") or 5.0),
                float(h.get("hz") or 99.0),
            ),
        )
        return {"folded": folded}, []

    async def rpc_xla_profile(self, h, frames, conn):
        """XLA/TPU profiler capture on this (chip-owning) worker."""
        from ray_tpu.util.debug import xla_profile_capture

        loop = asyncio.get_running_loop()
        res = await loop.run_in_executor(
            None,
            lambda: xla_profile_capture(
                float(h.get("duration_s") or 3.0), h.get("logdir")
            ),
        )
        return res, []

    async def rpc_run_control(self, h, frames, conn):
        """Run a pickled zero-arg callable on this process's control loop —
        internal hook for tests and the chaos killer."""
        fn = cloudpickle.loads(frames[0])
        res = fn()
        if asyncio.iscoroutine(res):
            res = await res
        return {}, [cloudpickle.dumps(res)]

    async def rpc_shutdown(self, h, frames, conn):
        self._shutdown = True
        loop = asyncio.get_running_loop()
        loop.call_later(0.05, loop.stop)
        return {}, []

    def shutdown(self):
        self._shutdown = True
        # Round 20 planes drain BEFORE transports tear down: a queued
        # reply frame still settles (its futures fail later with the
        # connections if the peer is already gone), and a queued packed
        # submit either dispatches or fails with the loop — never lost
        # silently in a worker thread.
        if self._pack_plane is not None:
            self._pack_plane.close()
            self._pack_plane = None
        if self._settle_plane is not None:
            for c in list(self.peers.values()):
                c.settle_plane = None
            for rc in list(self._ring_peers.values()):
                if rc is not False:
                    rc.settle_plane = None
            if self.gcs is not None:
                self.gcs.settle_plane = None
            self._settle_plane.close()
            self._settle_plane = None
        # Reply windows first, while every transport is still up: results
        # buffered behind an in-flight ack (short-lived executors, a
        # graceful remove_node drain) must reach their submitters before
        # connections start tearing down.
        self._flush_reply_windows()
        ObjectRef._release_hook = None
        with self._env_exec_lock:
            for ex in self._env_executors.values():
                ex.close()
            self._env_executors.clear()
        if self.xfer_addr is not None:
            try:
                from ray_tpu.native import xfer as native_xfer

                native_xfer.stop_server(self.xfer_addr[1])
            except Exception:
                pass
            self.xfer_addr = None
        if self.loop is None:
            return

        async def _close():
            if self.gcs is not None and self._task_events_buf:
                # Clean-shutdown flush: a short-lived driver's tail events
                # (< one 0.25s flusher tick old) must reach the head's
                # ring before the connection drops. A call (not notify)
                # so delivery is confirmed before teardown proceeds.
                batch, self._task_events_buf = self._task_events_buf, []
                try:
                    await asyncio.wait_for(
                        self.gcs.call("task_events", {"events": batch}),
                        timeout=2.0,
                    )
                except Exception as e:
                    logger.debug("final task-event flush failed: %s", e)
            try:
                for rc in list(self._ring_peers.values()):
                    if rc is not False:
                        rc._teardown()
                for rc in self._served_rings:
                    rc._teardown()
                for c in list(self.peers.values()):
                    await c.close()
                if self.gcs is not None:
                    await self.gcs.close()
                if self.server is not None:
                    await self.server.close()
            except Exception:
                pass
            if self._shm is not None:
                self._shm.close_all()
            # Quiet teardown: cancel stragglers (reapers, recv loops).
            me = asyncio.current_task()
            for t in asyncio.all_tasks():
                if t is not me:
                    t.cancel()

        try:
            fut = asyncio.run_coroutine_threadsafe(_close(), self.loop)
            fut.result(timeout=5)
        except Exception:
            pass
        for shard in self._pusher_loops:
            try:
                shard.call_soon_threadsafe(shard.stop)
            except RuntimeError:
                pass  # already stopped
        for t in self._pusher_threads:
            t.join(timeout=2)
        self._pusher_loops = []
        self._pusher_threads = []
        if self.loop_thread is not None:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.loop_thread.join(timeout=5)


# The process-global worker (reference: ``python/ray/_private/worker.py``
# global_worker). Set by ``ray_tpu.init`` / worker_main.
global_worker: Optional[CoreWorker] = None


def get_global_worker() -> CoreWorker:
    if global_worker is None:
        raise exc.RayTpuError(
            "ray_tpu has not been initialized; call ray_tpu.init() first"
        )
    return global_worker
