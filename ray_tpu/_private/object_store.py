"""Shared-memory object plane (plasma analog).

Reference: ``src/ray/object_manager/plasma`` — a shm arena owned by the raylet,
clients map segments and read zero-copy. Our single-machine round-1 design:

- Every *large* object is one POSIX shm segment (``/dev/shm``), created and
  written once by the producing process, attached read-only (zero-copy) by
  consumers. Layout: [u32 nframes][u64 len]*nframes then the frame payloads,
  8-byte aligned, so pickle5 out-of-band buffers deserialize as views into the
  mapping — a ``numpy``/``jax`` host array read costs no copies.
- The object *directory* (id → segment metadata) lives in the head service
  (``gcs.py`` object_dir), standing in for the reference's
  ``OwnershipObjectDirectory``.
- The native C++ arena store (``ray_tpu/native/``) slots in behind the same
  interface for allocation-rate-bound workloads; this file is the portable
  fallback and the protocol owner.

Small objects never come here — they live in the owner's in-process memory
store and travel inline (reference: CoreWorkerMemoryStore).
"""
from __future__ import annotations

import logging
import struct
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_ALIGN = 8
_HDR_COUNT = struct.Struct("<I")
_HDR_LEN = struct.Struct("<Q")

# Segments whose name was freed but whose mapping may still back live
# zero-copy views. Never GC'd: the mapping must outlive any exported pointer;
# it is reclaimed at process exit (matches plasma's mmap lifetime).
_graveyard: List[shared_memory.SharedMemory] = []


def graveyard_stats() -> dict:
    """Count/bytes of freed-but-still-mapped segments in this process —
    deliberately unreclaimed memory that MUST be visible to the metrics
    plane (rt_arena_graveyard_* gauges), or zero-copy-heavy workloads
    read as mystery RSS growth."""
    n = b = 0
    for shm in list(_graveyard):
        n += 1
        b += int(getattr(shm, "size", 0) or 0)
    return {"segments": n, "bytes": b}


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _unregister_tracker(shm: shared_memory.SharedMemory):
    """Detach this segment from the resource_tracker: lifetime is managed by
    the framework's distributed refcount, not by whichever process happened to
    touch the segment last (the tracker would unlink at process exit and
    double-warn)."""
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


def _safe_unlink(shm: shared_memory.SharedMemory):
    """unlink() itself unregisters with the tracker; re-register first so the
    tracker's bookkeeping stays balanced (we unregistered at create/attach)."""
    try:
        resource_tracker.register(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass
    shm.unlink()


class LocalShmStore:
    """Create/attach/free shm segments for serialized objects on this machine."""

    def __init__(self, prefix: str = "rt"):
        self.prefix = prefix
        # object hex -> (shm handle, pin count). Handles stay attached until
        # freed; readers may hold zero-copy views into them.
        self._segments: Dict[str, shared_memory.SharedMemory] = {}
        self._created: Dict[str, bool] = {}
        self._transient: set = set()  # safe to unmap fully on free

    def seg_name(self, object_hex: str) -> str:
        # shm names are limited (~255); object hex is 56 chars.
        return f"{self.prefix}_{object_hex}"

    def put_frames(self, object_hex: str, frames: List[bytes],
                   transient: bool = False) -> dict:
        """Write frames into a fresh segment; returns directory metadata.
        ``transient``: the producer guarantees no zero-copy views escape
        (readers copy on consume), so free() may fully unmap."""
        total = _HDR_COUNT.size + _HDR_LEN.size * len(frames)
        offsets = []
        for f in frames:
            total = _align(total)
            offsets.append(total)
            total += len(f)
        name = self.seg_name(object_hex)
        shm = shared_memory.SharedMemory(name=name, create=True, size=max(total, 1))
        _unregister_tracker(shm)
        buf = shm.buf
        _HDR_COUNT.pack_into(buf, 0, len(frames))
        pos = _HDR_COUNT.size
        for f in frames:
            _HDR_LEN.pack_into(buf, pos, len(f))
            pos += _HDR_LEN.size
        for off, f in zip(offsets, frames):
            buf[off : off + len(f)] = f
        self._segments[object_hex] = shm
        self._created[object_hex] = True
        meta = {"seg": name, "size": total}
        if transient:
            self._transient.add(object_hex)
            meta["transient"] = 1
        return meta

    def get_frames(self, object_hex: str, meta: dict) -> Optional[List[memoryview]]:
        """Attach and return zero-copy frame views (None if segment is gone)."""
        shm = self._segments.get(object_hex)
        if shm is None:
            try:
                shm = shared_memory.SharedMemory(name=meta["seg"], create=False)
            except FileNotFoundError:
                return None
            _unregister_tracker(shm)
            self._segments[object_hex] = shm
            self._created[object_hex] = False
        buf = shm.buf
        nframes = _HDR_COUNT.unpack_from(buf, 0)[0]
        lens = []
        pos = _HDR_COUNT.size
        for _ in range(nframes):
            lens.append(_HDR_LEN.unpack_from(buf, pos)[0])
            pos += _HDR_LEN.size
        frames = []
        for ln in lens:
            pos = _align(pos)
            frames.append(buf[pos : pos + ln])
            pos += ln
        return frames

    def contains(self, object_hex: str) -> bool:
        return object_hex in self._segments

    def created_stats(self) -> dict:
        """Count/bytes of segments this process created and still holds —
        the per-process contribution to node store utilization (segments
        attached read-only are the creator's bytes, not ours)."""
        n = b = 0
        for hex_, created in list(self._created.items()):
            if not created:
                continue
            shm = self._segments.get(hex_)
            if shm is None:
                continue
            n += 1
            b += int(getattr(shm, "size", 0) or 0)
        return {"objects": n, "bytes": b}

    def created_oids(self) -> List[str]:
        return [h for h, c in list(self._created.items()) if c]

    def free(self, object_hex: str, meta: Optional[dict] = None):
        shm = self._segments.pop(object_hex, None)
        created = self._created.pop(object_hex, False)
        if shm is None and meta is not None:
            try:
                shm = shared_memory.SharedMemory(name=meta["seg"], create=False)
                _unregister_tracker(shm)
                created = True
            except FileNotFoundError:
                return
        if shm is None:
            return
        try:
            if created:
                _safe_unlink(shm)
        except FileNotFoundError:
            pass
        transient = (
            (meta is not None and meta.get("transient"))
            or object_hex in self._transient
        )
        self._transient.discard(object_hex)
        if transient:
            # The producer declared no zero-copy views escape this segment
            # (e.g. DAG device-channel payloads — readers device_put a
            # copy): unmap now. Without this, per-step channel payloads
            # would grow resident memory for the process's lifetime.
            try:
                shm.close()
            except Exception:
                pass
            return
        # We do NOT shm.close(): readers may still hold zero-copy views into
        # the mapping. Unlink removes the name; the mapping dies with us.
        _graveyard.append(shm)

    def close_all(self):
        for hex_, shm in list(self._segments.items()):
            try:
                if self._created.get(hex_):
                    _safe_unlink(shm)
            except FileNotFoundError:
                pass
            _graveyard.append(shm)
        self._segments.clear()
        self._created.clear()
