"""cgroup resource isolation for spawned worker processes.

Reference analog: ``src/ray/common/cgroup2/`` (``cgroup_manager.h``,
``sysfs_cgroup_driver.cc``) — the reference carves a cgroup2 subtree per
node, moving worker processes under it with cpu weights and memory limits
so a runaway workload cannot take down the host services. Enabled
explicitly (the reference gates on ``enable_resource_isolation``); here the
switch is ``RT_CGROUP_ISOLATION=1`` on ``init``/``rt start``.

TPU-era notes: the process-per-host worker owns the TPU chips, so the
interesting limits are host memory (protect the head/daemon from worker
OOM) and CPU weight (keep input pipelines from starving control). Pure
cgroup2 hosts use ``cpu.max``/``memory.max``; v1-only hosts (common in
container images where v2 controllers are claimed by the host) fall back
to the v1 ``cpu``/``memory`` hierarchies. No permissions → cleanly
disabled, never an error: isolation is an operator upgrade, not a
correctness dependency.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_V2_ROOT = "/sys/fs/cgroup"
_V1_CPU = "/sys/fs/cgroup/cpu"
_V1_MEM = "/sys/fs/cgroup/memory"


def _writable_dir(path: str) -> bool:
    return os.path.isdir(path) and os.access(path, os.W_OK)


def _write(path: str, value: str) -> bool:
    try:
        with open(path, "w") as f:
            f.write(value)
        return True
    except OSError:
        return False


class CgroupDriver:
    """Creates per-worker cgroups and moves pids into them."""

    def __init__(self, base_name: str = "ray_tpu"):
        self.base = base_name
        self.mode = self._detect()

    @staticmethod
    def _detect() -> Optional[str]:
        try:
            with open(os.path.join(_V2_ROOT, "cgroup.controllers")) as f:
                ctrl = f.read().split()
            if ("cpu" in ctrl or "memory" in ctrl) and _writable_dir(
                _V2_ROOT
            ):
                return "v2"
        except OSError:
            pass
        if _writable_dir(_V1_CPU) or _writable_dir(_V1_MEM):
            return "v1"
        return None

    @property
    def available(self) -> bool:
        return self.mode is not None

    # -- lifecycle --------------------------------------------------------

    def create(self, name: str, *, cpu_shares: Optional[float] = None,
               memory_limit_bytes: Optional[int] = None):
        """Create a cgroup; returns an opaque handle (or None when
        unavailable). ``cpu_shares``: relative weight in CPUs (1.0 = one
        CPU's default weight); ``memory_limit_bytes``: hard cap."""
        if self.mode is None:
            return None
        paths = []
        # Every REQUESTED limit must actually land: controllers can be
        # advertised but not delegated (cgroup2 subtree_control in
        # containers), in which case the limit file does not exist and the
        # write fails — "created" with no cap would be silent non-isolation.
        applied_ok = True
        try:
            if self.mode == "v2":
                path = os.path.join(_V2_ROOT, f"{self.base}_{name}")
                os.makedirs(path, exist_ok=True)
                if cpu_shares is not None:
                    # cgroup2 cpu.weight: 1..10000, default 100 per unit
                    applied_ok &= _write(
                        os.path.join(path, "cpu.weight"),
                        str(max(1, min(10000, int(cpu_shares * 100)))))
                if memory_limit_bytes is not None:
                    applied_ok &= _write(
                        os.path.join(path, "memory.max"),
                        str(int(memory_limit_bytes)))
                paths.append(path)
            else:
                if cpu_shares is not None:
                    if _writable_dir(_V1_CPU):
                        p = os.path.join(_V1_CPU, f"{self.base}_{name}")
                        os.makedirs(p, exist_ok=True)
                        # v1 cpu.shares: default 1024 per unit
                        applied_ok &= _write(
                            os.path.join(p, "cpu.shares"),
                            str(max(2, int(cpu_shares * 1024))))
                        paths.append(p)
                    else:
                        applied_ok = False  # requested but no hierarchy
                if memory_limit_bytes is not None:
                    if _writable_dir(_V1_MEM):
                        p = os.path.join(_V1_MEM, f"{self.base}_{name}")
                        os.makedirs(p, exist_ok=True)
                        applied_ok &= _write(
                            os.path.join(p, "memory.limit_in_bytes"),
                            str(int(memory_limit_bytes)))
                        paths.append(p)
                    else:
                        applied_ok = False  # requested but no hierarchy
        except OSError as e:
            logger.debug("cgroup create %s failed: %s", name, e)
            self.remove(paths)
            return None
        if not paths or not applied_ok:
            self.remove(paths)
            return None
        return paths

    def add_pid(self, handle, pid: int) -> bool:
        if not handle:
            return False
        ok = False
        for path in handle:
            ok |= _write(os.path.join(path, "cgroup.procs"), str(pid))
        return ok

    def remove(self, handle) -> None:
        """Remove the cgroup(s); surviving member pids fall back to the
        parent group (kernel semantics: rmdir fails while populated, so
        members are migrated to the root first)."""
        if not handle:
            return
        for path in handle:
            try:
                procs_path = os.path.join(path, "cgroup.procs")
                root_procs = os.path.join(
                    os.path.dirname(path), "cgroup.procs"
                )
                with open(procs_path) as f:
                    for line in f:
                        pid = line.strip()
                        if pid:
                            _write(root_procs, pid)
                os.rmdir(path)
            except OSError:
                pass

    @staticmethod
    def pid_cgroups(pid: int):
        """The cgroup paths of a live pid (for tests/ops tooling)."""
        try:
            with open(f"/proc/{pid}/cgroup") as f:
                return f.read().splitlines()
        except OSError:
            return []


def enabled() -> bool:
    return os.environ.get("RT_CGROUP_ISOLATION") == "1"
