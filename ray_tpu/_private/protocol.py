"""Framed async RPC over TCP/unix sockets.

TPU-native analog of the reference's gRPC plumbing (``src/ray/rpc/``:
``grpc_server.h``, ``grpc_client.h``, ``retryable_grpc_client.cc``). The
control plane here is deliberately thin — msgpack headers + out-of-band binary
frames, pipelined request/reply with correlation ids over a single connection —
because on TPU pods the data plane lives inside XLA programs over ICI and the
control plane only has to be "good enough over DCN" (SURVEY.md §2.3).

Wire format per message:
    [u32 nframes][u32 len0][frame0][u32 len1][frame1]...
frame0 is a msgpack header: {i: correlation id, m: method | r: reply flag,
e: error}. Remaining frames are opaque binary payloads (pickle bytes, buffer
segments) that are never copied through msgpack.
"""
from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._private import faultpoints, flight
from ray_tpu._private.asyncio_util import spawn_logged

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<I")

# Keep per-message frame scatter small: writer.write once per message.


def encode_message(header: dict, frames: List[bytes]) -> bytes:
    hdr_bytes = msgpack.packb(header, use_bin_type=True)
    parts = [_HDR.pack(len(frames) + 1), _HDR.pack(len(hdr_bytes)), hdr_bytes]
    for f in frames:
        parts.append(_HDR.pack(len(f)))
        parts.append(f)
    return b"".join(parts)


def decode_message_bytes(data: bytes) -> Tuple[dict, List[bytes]]:
    """Decode one complete encoded message from a bytes buffer (the shm-ring
    transport delivers whole messages; same wire format as the TCP plane)."""
    nframes = _HDR.unpack_from(data, 0)[0]
    pos = 4
    frames: List[bytes] = []
    for _ in range(nframes):
        ln = _HDR.unpack_from(data, pos)[0]
        pos += 4
        frames.append(data[pos:pos + ln])
        pos += ln
    header = msgpack.unpackb(frames[0], raw=False)
    return header, frames[1:]


def _parse_buffered(buf) -> Optional[Tuple[dict, List[bytes], int]]:
    """Parse ONE complete wire message from the head of a bytearray (the
    asyncio ``StreamReader`` buffer, for the multi-frame settle drain):
    returns ``(header, frames, bytes_consumed)``, or None while the
    buffer holds only a partial message. Never consumes — the caller
    owns the ``del buf[:consumed]``."""
    blen = len(buf)
    if blen < 4:
        return None
    nframes = _HDR.unpack_from(buf, 0)[0]
    pos = 4
    spans = []
    for _ in range(nframes):
        if pos + 4 > blen:
            return None
        ln = _HDR.unpack_from(buf, pos)[0]
        pos += 4
        if pos + ln > blen:
            return None
        spans.append((pos, ln))
        pos += ln
    frames = [bytes(buf[p:p + ln]) for p, ln in spans]
    header = msgpack.unpackb(frames[0], raw=False)
    return header, frames[1:], pos


async def read_message(
    reader: asyncio.StreamReader, max_bytes: Optional[int] = None,
) -> Tuple[dict, List[bytes]]:
    """``max_bytes`` bounds total frame bytes (and frame count): an
    UNAUTHENTICATED peer must not be able to make readexactly allocate
    gigabytes before the auth gate ever sees the message."""
    nframes = _HDR.unpack(await reader.readexactly(4))[0]
    if max_bytes is not None and nframes > 16:
        raise ConnectionResetError("pre-auth message exceeds frame budget")
    frames: List[bytes] = []
    budget = max_bytes
    for _ in range(nframes):
        ln = _HDR.unpack(await reader.readexactly(4))[0]
        if budget is not None:
            budget -= ln
            if budget < 0:
                raise ConnectionResetError("pre-auth message too large")
        frames.append(await reader.readexactly(ln))
    header = msgpack.unpackb(frames[0], raw=False)
    return header, frames[1:]


# Sentinel a handler returns (as its ``extras``) when it has taken
# ownership of replying — e.g. the worker's reply window will deliver the
# result inside a coalesced multi-result frame. The dispatcher sends
# nothing; the handler MUST eventually answer the request's correlation
# id itself or the caller's deadline fires.
REPLY_HANDLED = object()


def pack_multi_frames(frame_lists: List[List[bytes]]) -> Tuple[List[int], List[bytes]]:
    """Flatten per-object frame lists into (counts, flat_frames) for a
    single wire message. Batched verbs (``pull_object_batch``) carry many
    objects' payloads in ONE framed message instead of one RPC per object;
    the counts ride in the msgpack header, the payload frames stay
    out-of-band and uncopied."""
    counts = []
    flat: List[bytes] = []
    for fl in frame_lists:
        counts.append(len(fl))
        flat.extend(fl)
    return counts, flat


def unpack_multi_frames(counts: List[int], frames: List[bytes]) -> List[List[bytes]]:
    """Inverse of :func:`pack_multi_frames`: split a flat frame list back
    into per-object frame lists."""
    out: List[List[bytes]] = []
    pos = 0
    for n in counts:
        out.append(frames[pos:pos + n])
        pos += n
    return out


class RpcError(Exception):
    """Remote handler failure. ``code`` is an optional machine-readable
    class (e.g. "oom") carried on the wire — callers branch on it, never on
    message substrings."""

    def __init__(self, message: str = "", code=None):
        super().__init__(message)
        self.code = code


class ConnectionLost(RpcError):
    pass


class Connection:
    """A bidirectional pipelined RPC connection.

    Either side may issue requests; replies are matched by correlation id.
    Incoming requests are dispatched to ``handler(method, header, frames)``
    which returns (reply_header_extras, reply_frames).
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Optional[
            Callable[[str, dict, List[bytes], "Connection"], Awaitable[tuple]]
        ] = None,
        name: str = "",
    ):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._next_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._recv_task: Optional[asyncio.Task] = None
        self.on_close: Optional[Callable[["Connection"], None]] = None
        self.peer_info: dict = {}  # set by registration handshakes
        # Set by accepting servers when cluster auth is on: the expected
        # token; cleared by a valid __auth first message.
        self.require_auth_token: Optional[str] = None
        # Write coalescing: send_raw buffers encoded messages and a single
        # call_soon callback flushes them next loop tick — a burst of small
        # RPCs (the task-submission hot loop) costs one send(2) instead of
        # one per message. Ordering is preserved; latency cost is one tick.
        # Buffered bytes are capped: at FLUSH_BYTES the flush happens
        # synchronously, so writer.drain() sees bulk traffic in the
        # transport and flow control still engages (at most FLUSH_BYTES per
        # connection are invisible to drain).
        self._out_buf: List[bytes] = []
        self._out_bytes = 0
        self._flush_scheduled = False
        # Round 16: multi-frame settling — inside a get()/wait() window
        # the recv loop drains every ALREADY-BUFFERED reply frame before
        # yielding, so one loop wakeup settles several coalesced frames'
        # futures. Gate read once per connection.
        from ray_tpu._private.config import rt_config

        self._settle_batching = bool(rt_config.settle_batching)
        # Settle economics (bench/tests): recv wakeups, frames settled,
        # frames drained beyond the first per wakeup, largest batch.
        self.settle_stats: Dict[str, int] = {
            "wakeups": 0, "frames": 0, "drained": 0, "max_batch": 0,
        }
        # Round 20: the driver attaches its SettlePlane here (None on
        # nodes and with RT_DRIVER_SETTLE_THREAD=0) — reply frames then
        # hand off to the plane thread instead of settling inline.
        self.settle_plane = None

    FLUSH_BYTES = 256 * 1024

    def start(self):
        self._loop = asyncio.get_running_loop()
        self._recv_task = self._loop.create_task(self._recv_loop())

    async def _recv_loop(self):
        try:
            while True:
                header, frames = await read_message(
                    self.reader,
                    max_bytes=(
                        4096 if self.require_auth_token is not None else None
                    ),
                )
                if self.require_auth_token is not None:
                    # Token auth (reference: src/ray/rpc/authentication/):
                    # the FIRST inbound message must be a valid __auth; a
                    # wrong or missing token closes the connection before
                    # any request is dispatched.
                    if (
                        not header.get("r")
                        and header.get("m") == "__auth"
                        and header.get("t") == self.require_auth_token
                    ):
                        self.require_auth_token = None
                        continue
                    logger.warning(
                        "rejecting unauthenticated connection (%s)",
                        self.name,
                    )
                    return  # finally: _teardown closes the socket
                if faultpoints.ACTIVE:
                    # error = connection reset mid-stream (outer except
                    # tears the connection down); drop = this message lost.
                    act = await faultpoints.async_fire(
                        "protocol.rpc.read", err=ConnectionResetError
                    )
                    if act == "drop":
                        continue
                batch = 1 + self._process_message(header, frames)
                if (self._settle_batching
                        and not faultpoints.ACTIVE
                        and self.require_auth_token is None):
                    # Multi-frame settling: everything the transport has
                    # ALREADY buffered settles in this same wakeup —
                    # several coalesced reply frames' futures per loop
                    # iteration inside a get()/wait() window. Chaos runs
                    # skip the drain so every message keeps riding the
                    # injected per-message read path (determinism).
                    batch += self._drain_buffered()
                st = self.settle_stats
                st["wakeups"] += 1
                st["frames"] += batch
                if batch > 1:
                    st["drained"] += batch - 1
                if batch > st["max_batch"]:
                    st["max_batch"] = batch
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        except Exception:
            logger.exception("rpc recv loop error (%s)", self.name)
        finally:
            self._teardown()

    def _process_message(self, header: dict, frames: List[bytes]) -> int:
        """Apply one inbound message (loop thread): settle replies, spawn
        dispatch tasks. Returns the number of EXTRA frames it settled
        beyond itself (always 0 — the return shape matches
        ``_drain_buffered`` call sites)."""
        if header.get("r"):  # reply
            # Arrival stamp, ALWAYS on for replies: the driver's push
            # windows clock their AIMD on push->arrival latency, and the
            # flight plane carves the arrival->settle dwell into the
            # pump-queue phase (both ends on the driver's clock).
            header.setdefault("_fr", time.monotonic())
            sp = self.settle_plane
            if sp is not None:
                # Round 20: hand the WHOLE coalesced frame to the settle
                # plane — splitting and future settling leave this loop.
                # The handoff stamp lands BEFORE the offer so the plane
                # thread can never observe an unstamped header; a
                # rejected offer (bounded queue full, chaos injection)
                # un-stamps and settles inline — degraded, never lost.
                header["_sq"] = time.monotonic()
                if sp.offer(self, (header, frames)):
                    return 0
                header.pop("_sq", None)
            if "bh" in header:
                # Coalesced multi-result frame: sub-replies ride
                # one message, each under its own correlation id
                # — N futures settle in this one wakeup.
                pos = 0
                fr_t = header.get("_fr")
                for sub, n in zip(header["bh"], header["bn"]):
                    if fr_t is not None:
                        sub["_fr"] = fr_t
                    self._settle_reply(sub, frames[pos:pos + n])
                    pos += n
                if header.get("wa"):
                    # Window ack: the sender's reply window clocks
                    # its next flush on this (the reply-side
                    # create_actor_batch discipline).
                    try:
                        self.notify("mrack")
                    except (RpcError, OSError) as e:
                        logger.debug(
                            "window ack dropped (%s): %s",
                            self.name, e,
                        )
            else:
                self._settle_reply(header, frames)
        else:
            if flight.ENABLED:
                # Arrival stamp: dispatch-side spans (and the head's
                # queue-wait attribution) measure from here.
                header["_fr"] = time.monotonic()
            spawn_logged(self._loop, self._dispatch(header, frames),
                         "protocol.dispatch")
        return 0

    def _drain_buffered(self) -> int:
        """Settle every COMPLETE message already sitting in the stream
        reader's buffer without yielding to the loop (no await, no
        readexactly coroutine per frame). Returns how many messages were
        drained. Falls back to 0 — the plain per-message path — when the
        reader's internals are not the expected CPython shape."""
        reader = self.reader
        buf = getattr(reader, "_buffer", None)
        if buf is None:
            return 0
        drained = 0
        while not self._closed:
            parsed = _parse_buffered(buf)
            if parsed is None:
                break
            header, frames, consumed = parsed
            del buf[:consumed]
            drained += 1
            self._process_message(header, frames)
        if drained:
            try:
                # Consuming from the buffer directly must re-open the
                # transport's flow control exactly like read() would.
                reader._maybe_resume_transport()
            except Exception as e:
                logger.debug("flow-control resume skipped: %s", e)
        return drained

    def _settle_reply(self, header: dict, frames: List[bytes]):
        fut = self._pending.pop(header["i"], None)
        if fut is not None and not fut.done():
            if header.get("e") is not None:
                fut.set_exception(
                    RpcError(header["e"], code=header.get("ec"))
                )
            else:
                fut.set_result((header, frames))

    # ----------------------------------------------- round-20 settle plane
    def _settle_prepare(self, payload):
        """SettlePlane contract, PLANE-THREAD side: split a coalesced
        reply frame into per-correlation subs off-loop. ``_pending`` has
        no lock (it is loop-thread state, iterated by ``_teardown``), so
        the pop + future settle stay on the loop in the returned apply
        op — the plane still wins: splitting happens here and N frames
        re-enter the loop as ONE scheduled call."""
        header, frames = payload
        flat = []
        ack = False
        if "bh" in header:
            pos = 0
            fr_t = header.get("_fr")
            sq_t = header.get("_sq")
            for sub, n in zip(header["bh"], header["bn"]):
                if fr_t is not None:
                    sub["_fr"] = fr_t
                if sq_t is not None:
                    sub["_sq"] = sq_t
                flat.append((sub, frames[pos:pos + n]))
                pos += n
            ack = bool(header.get("wa"))
        else:
            flat.append((header, frames))
        return [(self._loop, self._settle_apply_on_loop, (flat, ack))]

    def _settle_apply_on_loop(self, data):
        """Loop-side settle of plane-prepared subs. After teardown the
        pending futures were already failed with ConnectionLost — the
        pops all miss and this is a no-op, never a double settle."""
        flat, ack = data
        for sub, fr in flat:
            self._settle_reply(sub, fr)
        if ack and not self._closed:
            try:
                self.notify("mrack")
            except (RpcError, OSError) as e:
                logger.debug("window ack dropped (%s): %s", self.name, e)

    def _teardown(self):
        if self._closed:
            return
        self._flush()  # pending buffered messages go out before close
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            try:
                self.on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def _dispatch(self, header: dict, frames: List[bytes]):
        reply_header = {"i": header["i"], "r": 1}
        fl = flight.ENABLED
        if fl:
            t_arr = header.get("_fr") or time.monotonic()
            t_run = time.monotonic()
            fl_verb = f"rpc.s.{header.get('m')}"
            fl_out = "ok"
        try:
            extras, reply_frames = await self.handler(
                header["m"], header, frames, self
            )
            if extras is REPLY_HANDLED:
                # The handler routed its result into a coalesced reply
                # frame (worker reply window); nothing to send here.
                if fl:
                    flight.record_dispatch(fl_verb, "server", header, t_arr,
                                           t_run, 0, "windowed")
                return
            if extras:
                reply_header.update(extras)
        except faultpoints.DropReply:
            # Injected applied-but-unacknowledged failure: the handler ran
            # to completion, the caller gets silence (then a timeout).
            if fl:
                flight.record_dispatch(fl_verb, "server", header, t_arr,
                                       t_run, 0, "drop_reply")
            return
        except Exception as e:
            logger.debug("handler error for %s: %s", header.get("m"), e, exc_info=True)
            reply_header["e"] = f"{type(e).__name__}: {e}"
            code = getattr(e, "code", None)
            if code is not None:
                reply_header["ec"] = code
            reply_frames = []
            if fl:
                fl_out = f"error:{type(e).__name__}"
        if fl:
            flight.record_dispatch(
                fl_verb, "server", header, t_arr, t_run,
                sum(len(f) for f in reply_frames), fl_out,
            )
        if header.get("oneway"):
            return
        try:
            if faultpoints.ACTIVE:
                # error raises ConnectionResetError into the except below:
                # logged, no reply — indistinguishable from a peer that
                # vanished between request and ack.
                act = await faultpoints.async_fire(
                    "protocol.rpc.reply", err=ConnectionResetError
                )
                if act == "drop":
                    return
            self.send_raw(reply_header, reply_frames)
            # replies are latency-critical (a sync caller is blocked on this
            # round trip): flush now instead of waiting for the tick
            self._flush()
            await self.writer.drain()
        except (ConnectionLost, ConnectionResetError, OSError) as e:
            logger.debug(
                "reply for %s seq=%s dropped, peer gone: %s",
                header.get("method"), header.get("seq"), e,
            )

    def send_reply_batch(self, subs: List[dict], counts: List[int],
                         frames: List[bytes], extras: Optional[dict] = None):
        """Reply to many requests in ONE wire message (any thread).
        ``subs[k]`` carries its request's correlation id under ``i`` (and
        per-item ``e``/``ec`` for failures); ``counts[k]`` frames belong
        to it. The receiver's reply branch settles every sub-future in a
        single recv wakeup."""
        header = {"r": 1, "bh": subs, "bn": counts}
        if extras:
            header.update(extras)
        self.send_raw(header, list(frames))
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            # Replies stay latency-critical even coalesced: flush this
            # tick. Off-loop callers already marshalled the enqueue; the
            # scheduled tick flush covers them.
            self._flush()

    def send_raw(self, header: dict, frames: List[bytes]):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        data = encode_message(header, frames)
        # Off-loop callers (e.g. a notify() from a task-executor thread)
        # marshal the WHOLE enqueue onto the loop: an off-loop append would
        # race _flush's buffer swap and silently drop the message.
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        if not on_loop:
            if self._loop is None:
                raise ConnectionLost(f"connection {self.name} not started")
            self._loop.call_soon_threadsafe(self._enqueue_on_loop, data)
            return
        self._enqueue_on_loop(data)

    def _enqueue_on_loop(self, data: bytes):
        """Append + flush scheduling; loop thread only."""
        if self._closed:
            return
        self._out_buf.append(data)
        self._out_bytes += len(data)
        if self._out_bytes >= self.FLUSH_BYTES:
            self._flush()  # bulk payloads reach the transport before drain()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self):
        self._flush_scheduled = False
        if not self._out_buf:
            return
        buf, self._out_buf = self._out_buf, []
        self._out_bytes = 0
        if self._closed:
            return
        try:
            self.writer.write(buf[0] if len(buf) == 1 else b"".join(buf))
        except Exception:
            pass  # transport gone; the recv loop tears the connection down

    async def call(
        self, method: str, extras: Optional[dict] = None, frames: List[bytes] = ()
    ) -> Tuple[dict, List[bytes]]:
        """Issue a request and await the reply (pipelined; many may be in flight)."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        self._next_id += 1
        cid = self._next_id
        header = {"i": cid, "m": method}
        if extras:
            header.update(extras)
        fl = flight.ENABLED
        if fl:
            # Join key for the peer's server-side span: PR 3's correlation
            # id when the verb carries one, else a fresh flight id.
            fl_cid = header.get("corr") or header.get("fid")
            if fl_cid is None:
                fl_cid = header["fid"] = flight.next_id()
            fl_t0 = time.monotonic()
            fl_bytes = sum(len(f) for f in frames)
        fut = asyncio.get_running_loop().create_future()
        self._pending[cid] = fut
        try:
            dropped = False
            if faultpoints.ACTIVE:
                dropped = await faultpoints.async_fire(
                    "protocol.rpc.send", err=ConnectionLost
                ) == "drop"
            if not dropped:
                # drop: the request never reaches the wire; the caller's
                # deadline (not this coroutine) decides when to give up.
                self.send_raw(header, list(frames))
                try:
                    await self.writer.drain()
                except (ConnectionResetError, OSError):
                    pass
        except BaseException:
            self._pending.pop(cid, None)
            raise
        try:
            res = await fut
            if fl:
                flight.record(f"rpc.c.{method}", fl_cid, "client", fl_t0,
                              time.monotonic(), fl_bytes, "ok")
            return res
        except RpcError as e:
            if fl:
                flight.record(f"rpc.c.{method}", fl_cid, "client", fl_t0,
                              time.monotonic(), fl_bytes,
                              f"error:{type(e).__name__}")
            raise
        finally:
            # A cancelled wait (deadline-bounded callers wrap this in
            # wait_for) must not leave a dead entry keyed by cid for the
            # connection's lifetime; on the normal path the recv loop
            # already popped it and this is a no-op.
            self._pending.pop(cid, None)

    def notify(self, method: str, extras: Optional[dict] = None, frames=()):
        """Fire-and-forget request (no reply expected)."""
        self._next_id += 1
        header = {"i": self._next_id, "m": method, "oneway": 1}
        if extras:
            header.update(extras)
        self.send_raw(header, list(frames))

    async def close(self):
        self._teardown()
        if self._recv_task is not None:
            self._recv_task.cancel()


class RpcServer:
    """Asyncio TCP server dispatching to a method table."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: List[Connection] = []
        self.on_connection: Optional[Callable[[Connection], None]] = None

    async def start(self) -> Tuple[str, int]:
        # Pin the expected token at START: a server's trust anchor must
        # not drift with later env changes in the process.
        self.auth_token = _auth_token()
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port
        )
        sock = self._server.sockets[0]
        self.host, self.port = sock.getsockname()[:2]
        return self.host, self.port

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self.handler, name="server-accept")
        tok = getattr(self, "auth_token", "")
        if tok:
            conn.require_auth_token = tok
        conn.on_close = lambda c: (
            self.connections.remove(c) if c in self.connections else None
        )
        self.connections.append(conn)
        if self.on_connection:
            self.on_connection(conn)
        conn.start()

    async def close(self):
        for c in list(self.connections):
            await c.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


def _auth_token() -> str:
    from ray_tpu._private.config import rt_config

    return rt_config.auth_token


async def connect(
    addr: Tuple[str, int], handler=None, name: str = ""
) -> Connection:
    reader, writer = await asyncio.open_connection(addr[0], addr[1])
    try:
        writer.get_extra_info("socket").setsockopt(
            __import__("socket").IPPROTO_TCP, __import__("socket").TCP_NODELAY, 1
        )
    except Exception:
        pass
    conn = Connection(reader, writer, handler, name=name or f"client->{addr}")
    tok = _auth_token()
    if tok:
        # Both directions of a connection serve RPCs, so the accepting
        # side expects the token as our first message; ordered streams
        # guarantee it precedes every call queued after connect().
        conn.require_auth_token = None
        conn.start()
        conn.notify("__auth", {"t": tok})
        return conn
    conn.start()
    return conn
