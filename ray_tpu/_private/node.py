"""Cluster bootstrap: start the head service and spawn node processes.

Reference analog: ``python/ray/_private/node.py`` (``Node.start_head_processes``
:1344, ``start_raylet`` :1144) + ``services.py``. Round-1 shape: the head
service runs on the driver's core event loop (same RPC surface as an external
head, so it can be moved out-of-process later); nodes are subprocesses.
"""
from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu._private.backoff import Backoff
from ray_tpu._private.ids import JobID, NodeID

import logging

logger = logging.getLogger(__name__)


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: str, resources: dict,
                 cgroup=None, cgroup_driver=None):
        self.proc = proc
        self.node_id = node_id
        self.resources = resources
        self.cgroup = cgroup
        self._cgroup_driver = cgroup_driver

    def _drop_cgroup(self):
        if self.cgroup and self._cgroup_driver is not None:
            self._cgroup_driver.remove(self.cgroup)
            self.cgroup = None

    def kill(self, sig=None):
        try:
            self.proc.kill()
        except ProcessLookupError:
            pass
        self._drop_cgroup()

    def terminate(self):
        try:
            self.proc.terminate()
        except ProcessLookupError:
            pass
        self._drop_cgroup()

    def alive(self) -> bool:
        if self.proc.poll() is None:
            return True
        # the process died on its own: its cgroup must not outlive it
        self._drop_cgroup()
        return False


def spawn_node(
    gcs_addr,
    job_id: JobID,
    resources: Dict[str, float],
    labels: Optional[Dict[str, str]] = None,
    env: Optional[Dict[str, str]] = None,
    log_level: str = "WARNING",
) -> NodeHandle:
    node_id = NodeID.from_random().hex()
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu._private.worker_main",
        "--gcs-host", gcs_addr[0],
        "--gcs-port", str(gcs_addr[1]),
        "--resources", json.dumps(resources),
        "--labels", json.dumps(labels or {}),
        "--job-id", job_id.hex(),
        "--node-id", node_id,
        "--log-level", log_level,
    ]
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    # Log plane: every spawned worker gets a session dir to redirect its
    # stdio into (worker_main + log_monitor). init() passes a timestamped
    # one; standalone spawns (rt start, autoscaler local provider) default
    # to a per-head dir so all of a cluster's workers share one place.
    child_env.setdefault(
        "RT_SESSION_DIR", f"/tmp/ray_tpu/session_p{gcs_addr[1]}"
    )
    # Node processes must not inherit a driver-held TPU.
    proc = subprocess.Popen(cmd, env=child_env)
    cgroup = driver = None
    from ray_tpu._private import cgroups

    if cgroups.enabled():
        # Resource isolation (reference: cgroup2/cgroup_manager.h, gated
        # like enable_resource_isolation): CPU weight from the node's CPU
        # resource; memory capped at the node's memory resource when the
        # operator declared one. Unavailable/unwritable -> disabled.
        driver = cgroups.CgroupDriver()
        mem = resources.get("memory")
        cgroup = driver.create(
            node_id[:12],
            cpu_shares=resources.get("CPU"),
            memory_limit_bytes=int(mem) if mem else None,
        )
        if cgroup and not driver.add_pid(cgroup, proc.pid):
            driver.remove(cgroup)
            cgroup = None
        if cgroup is None and driver.available:
            logger.warning("cgroup isolation requested but not applied "
                           "for node %s", node_id[:8])
    return NodeHandle(proc, node_id, resources, cgroup, driver)


class LocalCluster:
    """In-process test/single-machine cluster (reference analog:
    ``python/ray/cluster_utils.py:137 Cluster`` — multi-node simulated by
    multiple node processes on one machine)."""

    def __init__(self, head_service, gcs_addr, job_id: JobID, driver_worker,
                 session_dir: Optional[str] = None):
        self.head = head_service
        self.gcs_addr = gcs_addr
        self.job_id = job_id
        self.driver = driver_worker
        self.session_dir = session_dir
        self.nodes: List[NodeHandle] = []
        atexit.register(self.shutdown)

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        env: Optional[Dict[str, str]] = None,
        wait: bool = True,
    ) -> NodeHandle:
        resources = dict(resources or {"CPU": 1})
        resources.setdefault("CPU", 1)
        # Added nodes log into the SAME session dir as init-spawned ones —
        # a cluster's log files must not split across two dirs.
        if self.session_dir:
            env = dict(env or {})
            env.setdefault("RT_SESSION_DIR", self.session_dir)
        handle = spawn_node(self.gcs_addr, self.job_id, resources, labels, env)
        self.nodes.append(handle)
        if wait:
            self.wait_for_nodes(len(self.alive_node_ids_expected()))
        return handle

    def alive_node_ids_expected(self):
        return [n.node_id for n in self.nodes if n.alive()]

    def wait_for_nodes(self, count: int, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        poll = Backoff(base=0.02, cap=0.1)
        while time.monotonic() < deadline:
            alive = [n for n in self.head.nodes.values() if n.alive]
            if len(alive) >= count:
                return
            poll.sleep()
        raise TimeoutError(
            f"cluster: only {len([n for n in self.head.nodes.values() if n.alive])}"
            f"/{count} nodes registered"
        )

    def kill_node(self, handle: NodeHandle):
        handle.kill()
        deadline = time.monotonic() + 10
        poll = Backoff(base=0.02, cap=0.1)
        while time.monotonic() < deadline:
            info = self.head.nodes.get(handle.node_id)
            if info is None or not info.alive:
                return
            poll.sleep()

    def shutdown(self):
        atexit.unregister(self.shutdown)
        # Planned teardown: node-death events that follow are expected and
        # must not emit failure-looking warnings (they mask real failures
        # in bench/CI logs).
        if self.head is not None:
            self.head._shutting_down = True
        for n in self.nodes:
            n.terminate()
        deadline = time.monotonic() + 3
        for n in self.nodes:
            poll = Backoff(base=0.02, cap=0.1)
            while n.alive() and time.monotonic() < deadline:
                poll.sleep()
            if n.alive():
                n.kill()
        self.nodes.clear()
