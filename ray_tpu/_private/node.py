"""Cluster bootstrap: start the head service and spawn node processes.

Reference analog: ``python/ray/_private/node.py`` (``Node.start_head_processes``
:1344, ``start_raylet`` :1144) + ``services.py``. Round-1 shape: the head
service runs on the driver's core event loop (same RPC surface as an external
head, so it can be moved out-of-process later); nodes are subprocesses.
"""
from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu._private.backoff import Backoff
from ray_tpu._private.ids import JobID, NodeID

import logging

logger = logging.getLogger(__name__)


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: str, resources: dict,
                 cgroup=None, cgroup_driver=None, standby: bool = False):
        self.proc = proc
        self.node_id = node_id
        self.resources = resources
        self.cgroup = cgroup
        self._cgroup_driver = cgroup_driver
        # True for warm-pool members spawned with --standby: until the
        # head confirms activation they will register scheduler-invisible,
        # so cluster-size accounting must not expect them to turn active.
        self.standby_spawn = standby

    def _drop_cgroup(self):
        if self.cgroup and self._cgroup_driver is not None:
            self._cgroup_driver.remove(self.cgroup)
            self.cgroup = None

    def kill(self, sig=None):
        try:
            self.proc.kill()
        except ProcessLookupError:
            pass
        self._drop_cgroup()

    def terminate(self):
        try:
            self.proc.terminate()
        except ProcessLookupError:
            pass
        self._drop_cgroup()

    def alive(self) -> bool:
        if self.proc.poll() is None:
            return True
        # the process died on its own: its cgroup must not outlive it
        self._drop_cgroup()
        return False


def spawn_node(
    gcs_addr,
    job_id: JobID,
    resources: Dict[str, float],
    labels: Optional[Dict[str, str]] = None,
    env: Optional[Dict[str, str]] = None,
    log_level: str = "WARNING",
    standby: bool = False,
) -> NodeHandle:
    node_id = NodeID.from_random().hex()
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu._private.worker_main",
        "--gcs-host", gcs_addr[0],
        "--gcs-port", str(gcs_addr[1]),
        "--resources", json.dumps(resources),
        "--labels", json.dumps(labels or {}),
        "--job-id", job_id.hex(),
        "--node-id", node_id,
        "--log-level", log_level,
    ]
    if standby:
        # Warm worker pool member: registers with the head but stays out
        # of the scheduler until activated (gcs._activate_standby).
        cmd.append("--standby")
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    # Log plane: every spawned worker gets a session dir to redirect its
    # stdio into (worker_main + log_monitor). init() passes a timestamped
    # one; standalone spawns (rt start, autoscaler local provider) default
    # to a per-head dir so all of a cluster's workers share one place.
    child_env.setdefault(
        "RT_SESSION_DIR", f"/tmp/ray_tpu/session_p{gcs_addr[1]}"
    )
    # Node processes must not inherit a driver-held TPU.
    proc = subprocess.Popen(cmd, env=child_env)
    cgroup = driver = None
    from ray_tpu._private import cgroups

    if cgroups.enabled():
        # Resource isolation (reference: cgroup2/cgroup_manager.h, gated
        # like enable_resource_isolation): CPU weight from the node's CPU
        # resource; memory capped at the node's memory resource when the
        # operator declared one. Unavailable/unwritable -> disabled.
        driver = cgroups.CgroupDriver()
        mem = resources.get("memory")
        cgroup = driver.create(
            node_id[:12],
            cpu_shares=resources.get("CPU"),
            memory_limit_bytes=int(mem) if mem else None,
        )
        if cgroup and not driver.add_pid(cgroup, proc.pid):
            driver.remove(cgroup)
            cgroup = None
        if cgroup is None and driver.available:
            logger.warning("cgroup isolation requested but not applied "
                           "for node %s", node_id[:8])
    return NodeHandle(proc, node_id, resources, cgroup, driver,
                      standby=standby)


class LocalCluster:
    """In-process test/single-machine cluster (reference analog:
    ``python/ray/cluster_utils.py:137 Cluster`` — multi-node simulated by
    multiple node processes on one machine)."""

    def __init__(self, head_service, gcs_addr, job_id: JobID, driver_worker,
                 session_dir: Optional[str] = None):
        self.head = head_service
        self.gcs_addr = gcs_addr
        self.job_id = job_id
        self.driver = driver_worker
        self.session_dir = session_dir
        self.nodes: List[NodeHandle] = []
        # Warm worker pool (rt_config.warm_workers): preforked STANDBY
        # node processes — registered, initialized, unschedulable until
        # activated. add_node() consumes one instead of a cold spawn; the
        # head auto-activates them when demand outgrows capacity.
        self.warm: List[NodeHandle] = []
        self.warm_resources: Dict[str, float] = {"CPU": 1}
        atexit.register(self.shutdown)

    def start_warm_pool(self, count: int,
                        resources: Optional[Dict[str, float]] = None,
                        env: Optional[Dict[str, str]] = None):
        """Prefork ``count`` standby node processes (non-blocking): they
        boot and register in the background, forming the instant-capacity
        reserve add_node() and the head's auto-activation draw from."""
        if resources:
            self.warm_resources = dict(resources)
        if self.session_dir:
            env = dict(env or {})
            env.setdefault("RT_SESSION_DIR", self.session_dir)
        for _ in range(max(count - len(self.warm), 0)):
            self.warm.append(spawn_node(
                self.gcs_addr, self.job_id, dict(self.warm_resources),
                env=env, standby=True,
            ))

    def _activate_warm(self, handle: NodeHandle,
                       timeout: float = 30.0) -> bool:
        """Ask the head to flip a standby node active; waits out the
        standby's registration if it is still booting."""
        deadline = time.monotonic() + timeout
        poll = Backoff(base=0.02, cap=0.25)
        while time.monotonic() < deadline and handle.alive():
            try:
                h = self.driver.run_sync(
                    self.driver._head_call(
                        "activate_node", {"node_id": handle.node_id}
                    ),
                    timeout=10,
                )[0]
            except Exception as e:
                logger.debug("warm activate %s failed: %s",
                             handle.node_id[:8], e)
                return False
            if h.get("found"):
                return True
            poll.sleep()  # not registered yet: still booting
        return False

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        env: Optional[Dict[str, str]] = None,
        wait: bool = True,
    ) -> NodeHandle:
        resources = dict(resources or {"CPU": 1})
        resources.setdefault("CPU", 1)
        # Warm fast path: an add matching a standby's OWN spawn spec (and
        # no custom labels/env) activates it — milliseconds instead of a
        # 2-4s cold process spawn. Matching per handle, not against
        # warm_resources: the pool can hold members preforked under an
        # earlier start_warm_pool spec.
        if not labels and not env:
            self.warm = [w for w in self.warm if w.alive()]
            wh = next(
                (w for w in self.warm if w.resources == resources), None
            )
            if wh is not None:
                self.warm.remove(wh)
                # Track it either way (shutdown must reap the process);
                # on activation failure it stays standby at the head, so
                # alive_node_ids_expected() won't count it and the cold
                # spawn below still satisfies wait_for_nodes.
                self.nodes.append(wh)
                if self._activate_warm(wh):
                    wh.standby_spawn = False
                    return wh
        # Added nodes log into the SAME session dir as init-spawned ones —
        # a cluster's log files must not split across two dirs.
        if self.session_dir:
            env = dict(env or {})
            env.setdefault("RT_SESSION_DIR", self.session_dir)
        handle = spawn_node(self.gcs_addr, self.job_id, resources, labels, env)
        self.nodes.append(handle)
        if wait:
            self.wait_for_nodes(len(self.alive_node_ids_expected()))
        return handle

    def alive_node_ids_expected(self):
        out = []
        for n in self.nodes:
            if not n.alive():
                continue
            # A tracked node the head still holds in the standby set (a
            # failed warm activation) is alive but by design invisible to
            # _head_active_nodes — counting it would make wait_for_nodes'
            # target unreachable. Same for a standby spawn that hasn't
            # registered yet (activation timed out pre-registration): it
            # will register AS STANDBY, never active. Unregistered cold
            # spawns count: they're booting toward active.
            info = self.head.nodes.get(n.node_id)
            if info is not None:
                if getattr(info, "standby", False):
                    continue
            elif getattr(n, "standby_spawn", False):
                continue
            out.append(n.node_id)
        return out

    def _head_active_nodes(self):
        """Registered, schedulable nodes in the head's view (standby pool
        members don't count toward expected cluster size)."""
        return [
            n for n in self.head.nodes.values()
            if n.alive and not getattr(n, "standby", False)
        ]

    def wait_for_nodes(self, count: int, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        poll = Backoff(base=0.02, cap=0.1)
        while time.monotonic() < deadline:
            if len(self._head_active_nodes()) >= count:
                return
            poll.sleep()
        raise TimeoutError(
            f"cluster: only {len(self._head_active_nodes())}"
            f"/{count} nodes registered"
        )

    def kill_node(self, handle: NodeHandle):
        handle.kill()
        deadline = time.monotonic() + 10
        poll = Backoff(base=0.02, cap=0.1)
        while time.monotonic() < deadline:
            info = self.head.nodes.get(handle.node_id)
            if info is None or not info.alive:
                return
            poll.sleep()

    def remove_node(self, handle: NodeHandle, timeout: float = 10.0):
        """Graceful (planned) node teardown: drain at the head FIRST —
        the head logs the departure at debug, reschedules nothing onto
        the node, and the subsequent connection close is a no-op — then
        terminate the process. ``kill_node`` stays the crash-test path
        (unannounced death, warning-level 'node dead')."""
        try:
            self.driver.run_sync(
                self.driver._head_call(
                    "drain_node", {"node_id": handle.node_id}
                ),
                timeout=10,
            )
        except Exception as e:
            logger.debug("drain_node %s failed: %s", handle.node_id[:8], e)
        handle.terminate()
        deadline = time.monotonic() + timeout
        poll = Backoff(base=0.02, cap=0.1)
        while handle.alive() and time.monotonic() < deadline:
            poll.sleep()
        if handle.alive():
            handle.kill()
        if handle in self.nodes:
            self.nodes.remove(handle)

    def shutdown(self):
        atexit.unregister(self.shutdown)
        # Planned teardown: node-death events that follow are expected and
        # must not emit failure-looking warnings (they mask real failures
        # in bench/CI logs).
        if self.head is not None:
            self.head._shutting_down = True
        doomed = self.nodes + self.warm
        for n in doomed:
            n.terminate()
        deadline = time.monotonic() + 3
        for n in doomed:
            poll = Backoff(base=0.02, cap=0.1)
            while n.alive() and time.monotonic() < deadline:
                poll.sleep()
            if n.alive():
                n.kill()
        self.nodes.clear()
        self.warm.clear()
