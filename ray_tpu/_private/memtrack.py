"""Object & memory observability plane: cluster-wide object accounting.

Reference shape: ``ray memory`` (``python/ray/_private/internal_api.py``
memory_summary over the ownership/refcount tables) joined to the plasma
store's per-node utilization counters. The RPC plane (flight recorder)
and the task plane (taskpath) are instrumented; this module covers the
third blind spot — the object plane — with the same design contract:

- **One-boolean gate.** Everything here is gated on the module attribute
  ``ENABLED`` (``rt_config.memtrack_enabled`` / ``RT_MEMTRACK_ENABLED``;
  ON by default — accounting is snapshot-time work, the put/get hot paths
  pay nothing either way). Disabled: metas stay unenriched, the 2s gauge
  tick skips, ``memstat_drain`` answers empty.
- **Snapshot-time accounting, not per-op bookkeeping.** A worker's object
  rows are derived from the structures the refcount plane already keeps
  (``owned`` / ``borrowed`` / ``memory_store`` / the arena's created
  index) when a drain or gauge tick asks — zero extra state on the
  put/free paths.
- **Owner-attributed bytes.** Each worker reports only objects it OWNS,
  so per-node sums across workers never double-count; arena-wide gauges
  (in_use/capacity/peak are one shared mapping per machine) roll up with
  ``max`` instead.

Surfaces: ``rt memory`` (``--group-by``, ``--leaks`` with nonzero exit
for CI), ``state.memory_summary()``, the dashboard objects page, and
``rt_object_store_bytes{node_id,kind}`` / ``rt_object_count{node_id,state}``
(+ spill/arena/graveyard/memory-pressure gauges) on the head's single
``/metrics`` scrape.

Leak model (the chaos matrices' zero-leaked-objects SLO): a directory
entry older than the grace window that no live process owns, holds in its
store, or borrows is a leak candidate — the owner died (or dropped its
record) and nothing keeps the object alive, yet the head still accounts
it. Borrower-held objects of a dead owner are NOT leaks: the borrow is
exactly what keeps them alive (``reference_counter.h`` semantics).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# The pinned memory_summary row schema (PARITY.md Round-13; consumers:
# `rt memory`, the dashboard objects page, the chaos leak SLO).
ROW_FIELDS = (
    "oid", "bytes", "kind", "state", "node", "owner", "owner_node",
    "task", "fn", "count", "borrows",
)

OBJECT_KINDS = ("inline", "shm", "spilled", "device")
OBJECT_STATES = ("owned", "pinned", "pending", "error", "borrowed")

GROUP_KEYS = ("owner", "node", "fn", "state", "kind", "task")


def _load_enabled() -> bool:
    try:
        from ray_tpu._private.config import rt_config

        return bool(rt_config.memtrack_enabled)
    except Exception as e:
        logger.debug("memtrack env config unavailable: %s", e)
        return True


# Hot-path gate: ``if memtrack.ENABLED: ...`` (same contract as
# flight.ENABLED — one attribute load and a false branch when off).
ENABLED = _load_enabled()


def enable():
    global ENABLED
    ENABLED = True


def disable():
    global ENABLED
    ENABLED = False


# ------------------------------------------------------------ worker side

def _device_staged_stats() -> Dict[str, int]:
    try:
        from ray_tpu._private import devstore

        return devstore.host_staged_stats()
    except Exception as e:  # devstore never blocks accounting
        logger.debug("devstore staging stats unavailable: %s", e)
        return {"count": 0, "bytes": 0}


def _object_row(oid: str, rec: dict, entry, node_id: str) -> Dict[str, Any]:
    """One owner-side accounting row from the refcount record + the
    memory-store entry (None while a task return is still in flight)."""
    kind, nbytes, node = "pending", 0, node_id
    if entry is not None:
        k = entry[0]
        if k == "mem":
            kind = "inline"
            nbytes = sum(len(f) for f in entry[1])
        elif k == "shm":
            meta = entry[1] or {}
            kind = "spilled" if "spill" in meta else "shm"
            nbytes = int(meta.get("size") or 0)
            # shm rows attribute to the node whose arena holds the
            # segment (a task return lives where it executed), not the
            # owner's node.
            node = meta.get("node") or node
        elif k == "dev":
            # Device-plane object: bytes live on the owner's accelerators
            # (devstore), never in a host arena.
            kind = "device"
            nbytes = int((entry[1] or {}).get("nbytes") or 0)
        else:
            kind = "error"
    return {
        "oid": oid, "bytes": nbytes, "kind": kind,
        "state": "pinned" if rec.get("borrows", 0) > 0 else "owned",
        "count": rec.get("count", 0),
        "borrows": rec.get("borrows", 0),
        "node": node,
    }


# Row cap per drained snapshot: a 1M-task burst leaves ~1M owned return
# records at the driver — shipping a row dict per object would be a
# multi-hundred-MB reply. Aggregates (bytes by kind/node, counts by
# state) stay EXACT in the same pass; only the per-object listing is
# truncated, and the drop is reported, never silent.
SNAPSHOT_MAX_ROWS = 50_000


def local_snapshot(worker,
                   max_rows: int = SNAPSHOT_MAX_ROWS) -> Dict[str, Any]:
    """This process's object accounting: owner-side rows (capped at
    ``max_rows`` with an honest dropped count; ``max_rows=0`` skips row
    building entirely — the gauge tick's aggregate-only mode), exact
    aggregates, borrow table, arena/graveyard/spill stats,
    created-object index, memory pressure. Dict reads are GIL-atomic
    snapshots (``list(d.items())`` never releases the GIL), so this is
    safe from the core loop or an executor thread."""
    from ray_tpu._private import memory_monitor

    ms_get = worker.memory_store.get
    node_id = worker.node_id
    my_node = str(node_id)[:12]
    objects: List[dict] = []
    by_kind_node: Dict[tuple, int] = {}
    by_state = {s: 0 for s in OBJECT_STATES}
    total = 0
    for oid, rec in list(worker.owned.items()):
        total += 1
        entry = ms_get(oid)
        # Aggregate inline (no row dict on this path — the common case
        # during a burst is a huge owned map of pending returns).
        if entry is None:
            by_state["pending"] += 1
            if len(objects) < max_rows:
                objects.append(_object_row(oid, rec, None, node_id))
            continue
        k = entry[0]
        if k == "mem":
            kind, nbytes, node = "inline", 0, my_node
            for f in entry[1]:
                nbytes += len(f)
        elif k == "shm":
            meta = entry[1] or {}
            kind = "spilled" if "spill" in meta else "shm"
            nbytes = int(meta.get("size") or 0)
            node = str(meta.get("node") or my_node)[:12]
        elif k == "dev":
            kind = "device"
            nbytes = int((entry[1] or {}).get("nbytes") or 0)
            node = my_node
        else:
            by_state["error"] += 1
            if len(objects) < max_rows:
                objects.append(_object_row(oid, rec, entry, node_id))
            continue
        key = (kind, node)
        by_kind_node[key] = by_kind_node.get(key, 0) + nbytes
        by_state["pinned" if rec.get("borrows", 0) > 0 else "owned"] += 1
        if len(objects) < max_rows:
            objects.append(_object_row(oid, rec, entry, node_id))
    borrowed = [
        {"oid": oid, "count": b.get("count", 0),
         "owner": list(b.get("owner") or ())}
        for oid, b in list(worker.borrowed.items())
    ]
    by_state["borrowed"] = len(borrowed)
    snap: Dict[str, Any] = {
        "worker": worker.worker_id.hex(),
        "node": node_id,
        "addr": list(worker.addr or ()),
        "is_driver": bool(worker.is_driver),
        "objects": objects,
        "objects_total": total,
        "objects_dropped": max(total - len(objects), 0),
        "bytes_by_kind_node": [
            [k, n, v] for (k, n), v in by_kind_node.items()
        ],
        "counts_by_state": by_state,
        "borrowed": borrowed,
        "store_oids": [],
        "arena": None,
        "fallback": {"objects": 0, "bytes": 0},
        "graveyard": {"segments": 0, "bytes": 0},
        "spill": {},
        # Device arrays that went through HOST serialization anyway
        # (plane off / nested in containers): their bytes already count
        # in the inline/shm rows above — this ledger says how much of
        # that host traffic is really device payload.
        "device_host_staged": _device_staged_stats(),
        "mem_used_ratio": memory_monitor.used_ratio(),
        "now": time.time(),
    }
    store = worker._shm
    if store is not None:
        st = store.stats()
        snap["arena"] = st.get("arena")
        snap["fallback"] = st.get("fallback") or snap["fallback"]
        snap["graveyard"] = st.get("graveyard") or snap["graveyard"]
        snap["spill"] = st.get("spill") or {}
        snap["store_oids"] = store.created_oids()
    return snap


_gauges: Optional[dict] = None


def _gauge_set() -> Optional[dict]:
    """Lazily register the object-plane gauge family (idempotent: the
    metrics registry canonicalizes re-registrations into one series)."""
    global _gauges
    if _gauges is not None:
        return _gauges
    try:
        from ray_tpu.util.metrics import Gauge

        _gauges = {
            "bytes": Gauge(
                "rt_object_store_bytes",
                description="Owner-accounted object bytes by kind "
                            "(inline|shm|spilled) and the node whose "
                            "store holds them",
                tag_keys=("kind", "node"),
            ),
            "count": Gauge(
                "rt_object_count",
                description="Owner-accounted object counts by ref state",
                tag_keys=("state",),
            ),
            "spill": Gauge(
                "rt_spill_bytes_total",
                description="Bytes spilled to external storage by this "
                            "process",
            ),
            "restore": Gauge(
                "rt_restore_bytes_total",
                description="Bytes restored from external storage by this "
                            "process",
            ),
            "arena": Gauge(
                "rt_arena_bytes",
                description="Native shm arena utilization (shared per "
                            "node; rolled up with max)",
                tag_keys=("what",),
            ),
            "grave_segs": Gauge(
                "rt_arena_graveyard_segments",
                description="Freed-but-mapped fallback segments "
                            "(deliberately unreclaimed; see object_store"
                            "._graveyard)",
            ),
            "grave_bytes": Gauge(
                "rt_arena_graveyard_bytes",
                description="Bytes held by freed-but-mapped fallback "
                            "segments",
            ),
            "mem_ratio": Gauge(
                "rt_node_memory_used_ratio",
                description="Node memory pressure (used/total; the OOM "
                            "admission threshold input)",
            ),
        }
    except Exception as e:
        logger.debug("memtrack gauges unavailable: %s", e)
    return _gauges


_prev_byte_keys: set = set()


def push_gauges(worker):
    """Refresh the object-plane gauges from a fresh local snapshot; they
    ride the existing metrics_push pipeline to the head's /metrics rollup.
    Every tag value is set each tick — and (kind, node) byte keys this
    process stopped reporting are zeroed explicitly — because stale gauge
    samples would otherwise report the last nonzero value forever."""
    global _prev_byte_keys
    g = _gauge_set()
    if g is None:
        return
    # Aggregate-only snapshot (max_rows=0): ONE pass over the owned map
    # with no row dicts — the 2s tick must stay cheap while a 1M-task
    # burst holds a million pending return records.
    snap = local_snapshot(worker, max_rows=0)
    # Bytes attribute to the node whose STORE holds the segment (the
    # sample-level "node" tag; the /metrics rollup groups on it) — a
    # task return is owned by the driver but its bytes sit in the
    # executing node's arena.
    my_node = str(worker.node_id)[:12]
    by_kind_node: Dict[tuple, float] = {
        (k, n): float(v) for k, n, v in snap["bytes_by_kind_node"]
    }
    by_state = {s: float(snap["counts_by_state"].get(s, 0))
                for s in OBJECT_STATES}
    for kind in OBJECT_KINDS:
        by_kind_node.setdefault((kind, my_node), 0.0)
    for key in _prev_byte_keys - set(by_kind_node):
        by_kind_node[key] = 0.0
    _prev_byte_keys = {k for k, v in by_kind_node.items() if v > 0.0}
    for (kind, node), v in by_kind_node.items():
        g["bytes"].set(v, tags={"kind": kind, "node": node})
    for state, v in by_state.items():
        g["count"].set(v, tags={"state": state})
    spill = snap.get("spill") or {}
    g["spill"].set(float(spill.get("spilled_bytes", 0)))
    g["restore"].set(float(spill.get("restored_bytes", 0)))
    arena = snap.get("arena")
    if arena:
        g["arena"].set(float(arena.get("bytes_in_use", 0)),
                       tags={"what": "in_use"})
        g["arena"].set(float(arena.get("capacity", 0)),
                       tags={"what": "capacity"})
        g["arena"].set(float(arena.get("peak_bytes", 0)),
                       tags={"what": "peak"})
    grave = snap.get("graveyard") or {}
    g["grave_segs"].set(float(grave.get("segments", 0)))
    g["grave_bytes"].set(float(grave.get("bytes", 0)))
    g["mem_ratio"].set(float(snap.get("mem_used_ratio", 0.0)))


# ------------------------------------------------------------- analysis

def build_summary(raw: Dict[str, Any], grace_s: float = 5.0,
                  now: Optional[float] = None) -> Dict[str, Any]:
    """Join the head's ``memory_summary`` verb reply (per-process
    snapshots + the object directory + the task-name map) into the
    cluster summary: object rows, per-node reconciliation, leak
    candidates, totals. Pure function of its input — unit-testable
    without a cluster."""
    snaps = raw.get("snapshots") or []
    directory = raw.get("directory") or []
    names = raw.get("tasks") or {}
    if now is None:
        now = float(raw.get("now") or time.time())
    rows: List[Dict[str, Any]] = []
    owned_at: Dict[str, dict] = {}
    borrow_count: Dict[str, int] = {}
    store_hold: set = set()
    agg_bytes: Dict[tuple, float] = {}  # (kind, node) exact, cap-proof
    rows_dropped = 0
    for s in snaps:
        addr = list(s.get("addr") or ())
        rows_dropped += int(s.get("objects_dropped") or 0)
        for o in s.get("objects") or ():
            tid = o["oid"][:48]
            row = {
                "oid": o["oid"], "bytes": int(o.get("bytes") or 0),
                "kind": o.get("kind") or "pending",
                "state": o.get("state") or "owned",
                "node": o.get("node") or s.get("node"),
                "owner": addr, "owner_node": s.get("node"),
                "task": tid, "fn": names.get(tid) or "",
                "count": o.get("count", 0), "borrows": o.get("borrows", 0),
            }
            rows.append(row)
            owned_at[o["oid"]] = row
        agg = s.get("bytes_by_kind_node")
        if agg is None:
            # Pre-aggregate snapshot shape: derive from the rows.
            agg = []
            for o in s.get("objects") or ():
                if o.get("kind") in OBJECT_KINDS:
                    agg.append([o["kind"],
                                str(o.get("node") or s.get("node"))[:12],
                                int(o.get("bytes") or 0)])
        for kind, node, v in agg:
            # Inline bytes live in the OWNER's memory, not a node store:
            # attribute them to the snapshot's node.
            key = (kind, str((s.get("node") if kind == "inline"
                              else node))[:12])
            agg_bytes[key] = agg_bytes.get(key, 0.0) + float(v)
        for b in s.get("borrowed") or ():
            borrow_count[b["oid"]] = (
                borrow_count.get(b["oid"], 0) + int(b.get("count") or 1)
            )
        store_hold.update(s.get("store_oids") or ())

    leaks: List[Dict[str, Any]] = []
    dir_bytes_by_node: Dict[str, Dict[str, float]] = {}
    for d in directory:
        oid, meta = d["oid"], d.get("meta") or {}
        node = str(meta.get("node") or "")[:12] or "?"
        if meta.get("device"):
            kind = "device"
        elif meta.get("spill"):
            kind = "spilled"
        else:
            kind = "shm"
        size = float(meta.get("size") or 0)
        pn = dir_bytes_by_node.setdefault(
            node, {"directory_shm_bytes": 0.0,
                   "directory_spilled_bytes": 0.0,
                   "directory_device_bytes": 0.0}
        )
        pn[f"directory_{kind}_bytes"] += size
        if oid in owned_at:
            owned_at[oid].setdefault("locations", []).append(node)
            continue
        if oid in store_hold or borrow_count.get(oid, 0) > 0:
            continue  # alive via a live store mapping or a borrower
        if not snaps or rows_dropped:
            # No accounting to judge liveness with (plane disabled), or
            # ownership listings were truncated (an unlisted owner row
            # would read as an orphan): flagging here would be noise,
            # not detection — leaks_truncated below says so.
            continue
        age = max(now - float(meta.get("_t") or now), 0.0)
        if age >= grace_s:
            tid = oid[:48]
            leaks.append({
                "oid": oid, "bytes": int(size), "kind": kind,
                "node": node, "owner": list(meta.get("owner") or ()),
                "task": tid, "fn": names.get(tid) or "", "age_s": age,
                "reason": "owner-gone",
            })

    reconcile: Dict[str, Dict[str, float]] = {}

    def pn(node) -> Dict[str, float]:
        return reconcile.setdefault(str(node or "?")[:12], {
            "owner_inline_bytes": 0.0, "owner_shm_bytes": 0.0,
            "owner_spilled_bytes": 0.0, "owner_device_bytes": 0.0,
            "directory_shm_bytes": 0.0, "directory_spilled_bytes": 0.0,
            "directory_device_bytes": 0.0, "arena_bytes_in_use": 0.0,
            "arena_peak_bytes": 0.0, "delta_shm_bytes": 0.0,
        })

    for (kind, node), v in agg_bytes.items():
        if kind == "inline":
            pn(node)["owner_inline_bytes"] += v
        elif kind == "shm":
            pn(node)["owner_shm_bytes"] += v
        elif kind == "spilled":
            pn(node)["owner_spilled_bytes"] += v
        elif kind == "device":
            pn(node)["owner_device_bytes"] += v
    for node, d in dir_bytes_by_node.items():
        rec = pn(node)
        rec["directory_shm_bytes"] += d["directory_shm_bytes"]
        rec["directory_spilled_bytes"] += d["directory_spilled_bytes"]
        rec["directory_device_bytes"] += d.get("directory_device_bytes", 0.0)
    for s in snaps:
        arena = s.get("arena")
        if not arena:
            continue
        rec = pn(s.get("node"))
        # The arena is ONE shared mapping per machine: every process on
        # the node reports the same counters, so max (not sum).
        rec["arena_bytes_in_use"] = max(
            rec["arena_bytes_in_use"], float(arena.get("bytes_in_use", 0))
        )
        rec["arena_peak_bytes"] = max(
            rec["arena_peak_bytes"], float(arena.get("peak_bytes", 0))
        )
    for rec in reconcile.values():
        rec["delta_shm_bytes"] = (
            rec["directory_shm_bytes"] - rec["owner_shm_bytes"]
        )

    totals = {
        "objects": len(rows) + rows_dropped,
        "inline_bytes": sum(
            v for (k, _n), v in agg_bytes.items() if k == "inline"
        ),
        "shm_bytes": sum(
            v for (k, _n), v in agg_bytes.items() if k == "shm"
        ),
        "spilled_bytes": sum(
            v for (k, _n), v in agg_bytes.items() if k == "spilled"
        ),
        "device_bytes": sum(
            v for (k, _n), v in agg_bytes.items() if k == "device"
        ),
        "directory_entries": int(
            raw.get("recorded") or len(directory)
        ),
        "arena_peak_bytes": sum(
            rec["arena_peak_bytes"] for rec in reconcile.values()
        ),
        "leak_candidates": len(leaks),
    }
    return {
        "enabled": bool(raw.get("enabled", bool(snaps))),
        "rows": rows,
        "rows_dropped": rows_dropped,
        # True when per-object listings were truncated: byte totals and
        # reconciliation above stay EXACT (single-pass aggregates), but
        # leak detection was skipped — an unlisted owner row would read
        # as an orphan.
        "leaks_truncated": bool(rows_dropped and snaps),
        "leaks": leaks,
        "reconcile": reconcile,
        "totals": totals,
        "directory_recorded": int(raw.get("recorded") or len(directory)),
        "directory_dropped": int(raw.get("dropped") or 0),
        "grace_s": grace_s,
    }


def group_rows(rows: List[Dict[str, Any]],
               by: str) -> Dict[str, Dict[str, Any]]:
    """Aggregate object rows by one of GROUP_KEYS (``rt memory
    --group-by``); owner groups render as host:port."""
    if by not in GROUP_KEYS:
        raise ValueError(f"group_by must be one of {GROUP_KEYS}, got {by!r}")
    out: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        key = r.get(by)
        if by == "owner":
            key = ":".join(str(p) for p in (key or ())) or "?"
        key = str(key or "?")
        g = out.setdefault(key, {"objects": 0, "bytes": 0, "pinned": 0})
        g["objects"] += 1
        g["bytes"] += r["bytes"]
        if r.get("state") == "pinned":
            g["pinned"] += 1
    return out


def memory_summary(address: Optional[str] = None,
                   group_by: Optional[str] = None,
                   grace_s: float = 5.0) -> Dict[str, Any]:
    """Cluster-wide object/memory summary: the head fans ``memstat_drain``
    to every process, and the reply is joined client-side (works from a
    driver or a bare CLI via the sync head client)."""
    from ray_tpu.util.state import _call

    raw = _call("memory_summary", {}, address, timeout=60.0)
    summary = build_summary(raw, grace_s=grace_s)
    if group_by:
        summary["groups"] = group_rows(summary["rows"], group_by)
        summary["group_by"] = group_by
    return summary


# ------------------------------------------------------------- rendering

def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024.0
    return f"{n:.1f}GiB"


def format_summary(s: Dict[str, Any], limit: int = 30) -> str:
    """Fixed-width report for ``rt memory``: totals, per-node
    reconciliation, heaviest rows, leak candidates."""
    t = s["totals"]
    lines = [
        f"objects={t['objects']}  inline={_fmt_bytes(t['inline_bytes'])}  "
        f"shm={_fmt_bytes(t['shm_bytes'])}  "
        f"spilled={_fmt_bytes(t['spilled_bytes'])}  "
        f"device={_fmt_bytes(t.get('device_bytes', 0))}  "
        f"directory={t['directory_entries']} entr"
        f"{'y' if t['directory_entries'] == 1 else 'ies'}  "
        f"leak-candidates={t['leak_candidates']}",
        "",
        f"{'node':<14}{'inline':>10}{'shm':>10}{'spilled':>10}"
        f"{'directory':>11}{'delta':>9}{'arena':>10}{'peak':>10}",
    ]
    for node, rec in sorted(s["reconcile"].items()):
        lines.append(
            f"{node:<14}"
            f"{_fmt_bytes(rec['owner_inline_bytes']):>10}"
            f"{_fmt_bytes(rec['owner_shm_bytes']):>10}"
            f"{_fmt_bytes(rec['owner_spilled_bytes']):>10}"
            f"{_fmt_bytes(rec['directory_shm_bytes']):>11}"
            f"{_fmt_bytes(rec['delta_shm_bytes']):>9}"
            f"{_fmt_bytes(rec['arena_bytes_in_use']):>10}"
            f"{_fmt_bytes(rec['arena_peak_bytes']):>10}"
        )
    groups = s.get("groups")
    if groups:
        lines += ["", f"{'group (' + s['group_by'] + ')':<34}"
                      f"{'objects':>9}{'pinned':>8}{'bytes':>12}"]
        top = sorted(groups.items(), key=lambda kv: -kv[1]["bytes"])
        for key, g in top[:limit]:
            lines.append(f"{key[:33]:<34}{g['objects']:>9}{g['pinned']:>8}"
                         f"{_fmt_bytes(g['bytes']):>12}")
    else:
        lines += ["", f"{'object':<18}{'kind':<9}{'state':<9}{'bytes':>10}"
                      f"  {'node':<10}{'fn':<18}{'task':<14}"]
        top = sorted(s["rows"], key=lambda r: -r["bytes"])
        for r in top[:limit]:
            lines.append(
                f"{r['oid'][:16]:<18}{r['kind']:<9}{r['state']:<9}"
                f"{_fmt_bytes(r['bytes']):>10}  "
                f"{str(r['node'])[:8]:<10}{(r['fn'] or '-')[:17]:<18}"
                f"{r['task'][:12]:<14}"
            )
        if len(s["rows"]) > limit:
            lines.append(f"... {len(s['rows']) - limit} more rows "
                         f"(--json for all)")
    if s["leaks"]:
        lines += ["", "LEAK CANDIDATES (owner gone, no borrower, past "
                      f"{s['grace_s']}s grace):"]
        for lk in s["leaks"][:limit]:
            lines.append(
                f"  {lk['oid'][:16]}  {_fmt_bytes(lk['bytes'])}  "
                f"node={str(lk['node'])[:8]}  fn={lk['fn'] or '-'}  "
                f"age={lk['age_s']:.1f}s"
            )
    if s.get("leaks_truncated"):
        lines.append(f"\nNOTE: {s.get('rows_dropped', 0)} object rows "
                     f"truncated (SNAPSHOT_MAX_ROWS) — byte totals stay "
                     f"exact, leak detection skipped this pass")
    if not s.get("enabled", True):
        lines.append("\nNOTE: no process reported accounting — is the "
                     "plane off (RT_MEMTRACK_ENABLED=0)?")
    return "\n".join(lines)
