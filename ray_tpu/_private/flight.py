"""Cross-process RPC flight recorder: per-process event ring + trace merge.

Reference shape: Dapper-class always-on sampling tracers (Sigelman et al.,
Google TR 2010) and the reference's own chrome-trace surface (``ray
timeline``). Task-level observability already exists (task events →
``rt timeline``; util/metrics → Prometheus); this module records one layer
below — the RPC **verb** plane (protocol send/reply, ring push/pop, head
dispatch, worker pulls/pushes) — where both open perf items in ROADMAP.md
actually spend their time.

Design contract (mirrors ``faultpoints``):

- **Off by default, one boolean per hook.** Every call site is gated on the
  module attribute ``ENABLED``; disabled, the hot paths pay one attribute
  load and a false branch.
- **Allocation-bounded when on.** Events live in a preallocated fixed-size
  ring (``rt_config.flight_ring_size``) as plain tuples, oldest overwritten;
  a drain reports how many were dropped. No dicts, no unbounded lists.
- **Lock-light.** A ``threading.Lock`` is held for exactly the slot store
  (two statements); histogram observation happens outside it.

Event tuple layout (fixed 8 fields, msgpack-able as a list)::

    (verb, cid, kind, t0, t1, nbytes, outcome, queue_wait)

- ``verb``: dotted hook name (``rpc.c.lease``, ``gcs.lease``, ``ring.push``,
  ``worker.pull``, ``head.create_actor``, ...)
- ``cid``: cross-process join key — PR 3's correlation id (``corr``) when the
  request carries one, else a per-process flight id (``fid``) stamped into
  the wire header so both ends of one RPC record the same key.
- ``kind``: span category (client | server | head | ring | worker | fault
  | task — the taskpath plane's per-task phase spans, cid = task id; see
  ``_private/taskpath.py``)
- ``t0``/``t1``: ``time.monotonic()`` span bounds in THIS process. Each
  process also records a (wall, mono) anchor; the merge step maps spans onto
  the head's wall clock with an RTT/2-corrected per-node offset.
- ``nbytes``: payload bytes on the wire for this span (0 when not metered)
- ``outcome``: ``ok`` | ``error:<Type>`` | ``timeout`` | ``drop_reply`` |
  ``fault_injected:<point>:<kind>`` (stamped by the faultpoints plane)
- ``queue_wait``: seconds between message arrival and handler start
  (head dispatch records it; 0.0 elsewhere)

The head verb ``flight_snapshot`` fans ``flight_drain`` out to every node,
clock-aligns the events and returns the raw snapshots; ``merge_snapshots`` /
``to_chrome_trace`` below turn them into a Chrome trace-event JSON that
loads in Perfetto / chrome://tracing (``rt flight --output``).
"""
from __future__ import annotations

import contextvars
import itertools
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# Hot-path gate: ``if flight.ENABLED: flight.record(...)``.
ENABLED = False

# Sampling: record 1 of every SAMPLE_N spans (0/1 = record all). The
# decision is a DETERMINISTIC counter, not an RNG draw — two identical
# runs sample identical call indices, so diffing sampled traces stays
# meaningful. At SAMPLE_N=0 the check is one falsy comparison and the
# counter is never touched (always-on production use pays a counter bump
# per skipped span, nothing else).
SAMPLE_N = 0
_sample_count = itertools.count(1)

_DEFAULT_RING = 16384

# Latency buckets: RPC verbs span ~50us (ring push) to ~30s (deadline).
_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class _Recorder:
    __slots__ = ("size", "buf", "n", "lock", "anchor_wall", "anchor_mono")

    def __init__(self, size: int):
        self.size = max(int(size), 1)
        self.buf: List[Optional[tuple]] = [None] * self.size
        self.n = 0  # total events ever recorded (wraps the ring modulo size)
        self.lock = threading.Lock()
        # Wall/monotonic anchor pair: the merge step converts monotonic span
        # bounds to wall time via ``anchor_wall + (t - anchor_mono)``.
        self.anchor_wall = time.time()
        self.anchor_mono = time.monotonic()


_rec: Optional[_Recorder] = None
_label: Optional[str] = None
# Pending fault stamp. A ContextVar, not a threading.local: faultpoints
# fire inside the same coroutine as the span being recorded, and
# coroutines interleave on the event-loop thread — task-local scoping
# keeps the stamp with the RPC it actually bit (on plain executor
# threads it degrades to exactly thread-local behavior).
_fault_pending: "contextvars.ContextVar[Optional[tuple]]" = (
    contextvars.ContextVar("rt_flight_fault", default=None)
)
_fid_counter = itertools.count(1)
# Process-unique token: snapshot identity across hosts (OS pids collide
# between machines; the head dedups drained snapshots by this).
_PROC_TOKEN = os.urandom(6).hex()
_hist_latency = None
_hist_qwait = None


def set_label(label: str):
    """Human-readable per-process label for merged traces (node id prefix,
    "driver", "head"). Safe to call whether or not recording is enabled."""
    global _label
    _label = label


def next_id() -> str:
    """Cheap process-unique flight id stamped into wire headers (``fid``)
    when the request has no PR-3 correlation id; both ends of the RPC then
    record the same join key."""
    return f"f{os.getpid():x}-{next(_fid_counter)}"


def set_sample_n(n: int):
    """Install the sampling divisor (``rt_config.flight_sample_n``): record
    1/N spans via a deterministic counter; 0/1 records everything. The
    counter restarts so the kept indices are a pure function of N."""
    global SAMPLE_N, _sample_count
    SAMPLE_N = max(int(n), 0)
    _sample_count = itertools.count(1)


def enable(ring_size: Optional[int] = None):
    """Start recording into a fresh preallocated ring. Idempotent-ish: a
    second enable with a different size replaces the ring (drains lost)."""
    global _rec, ENABLED, _hist_latency, _hist_qwait
    if ring_size is None:
        try:
            from ray_tpu._private.config import rt_config

            ring_size = int(rt_config.flight_ring_size)
        except Exception:
            ring_size = _DEFAULT_RING
    try:
        from ray_tpu._private.config import rt_config

        set_sample_n(int(rt_config.flight_sample_n))
    except Exception:
        set_sample_n(0)
    _rec = _Recorder(ring_size)
    # Per-verb latency / head queue-wait histograms ride the existing
    # metrics registry, so they reach /metrics and the dashboard through
    # the same worker metrics_push pipeline as every other series. Both
    # are assigned atomically (or neither): record() must never see a
    # half-registered pair.
    try:
        from ray_tpu.util.metrics import Histogram

        lat = Histogram(
            "rt_rpc_latency_s",
            description="RPC verb latency recorded by the flight recorder",
            boundaries=_LATENCY_BUCKETS,
            tag_keys=("verb",),
        )
        qw = Histogram(
            "rt_rpc_queue_wait_s",
            description="Head dispatch queue wait (arrival to handler start)",
            boundaries=_LATENCY_BUCKETS,
            tag_keys=("verb",),
        )
        _hist_latency, _hist_qwait = lat, qw
    except Exception as e:
        # Metrics must never block the recorder itself (e.g. a boundary
        # clash with an older registration); the ring still records.
        _hist_latency = _hist_qwait = None
        logger.debug("flight histograms unavailable: %s", e)
    ENABLED = True


def disable():
    global _rec, ENABLED
    ENABLED = False
    _rec = None
    _fault_pending.set(None)


def record(verb: str, cid, kind: str, t0: float, t1: float,
           nbytes: int = 0, outcome: str = "ok", qw: float = 0.0):
    """Append one span to the ring. Call sites gate on ``ENABLED`` so the
    disabled cost stays at one attribute load; a record racing disable() is
    simply dropped here."""
    r = _rec
    if r is None:
        return
    h = _hist_latency
    if h is not None:
        # /metrics histograms observe EVERY span regardless of sampling:
        # they were the always-on cost before flight_sample_n existed, and
        # count-based RPC-rate dashboards must not read 1/N low.
        h.observe(t1 - t0, tags={"verb": verb})
        if qw > 0.0 and _hist_qwait is not None:
            _hist_qwait.observe(qw, tags={"verb": verb})
    n = SAMPLE_N
    if n > 1 and kind != "fault" and next(_sample_count) % n:
        # Sampled out (deterministic 1/N keep). Fault instants always
        # record — chaos forensics must not lose injection evidence —
        # and a pending fault stamp stays armed for the next kept span
        # whose window covers it.
        return
    f = _fault_pending.get()
    if f is not None:
        # A fault injected in this task/thread context since this span
        # began annotates the span (satellite contract: chaos traces show
        # WHERE the plane bit). Faults from before the span stay with
        # their own instant event.
        if f[2] >= t0:
            outcome = f"fault_injected:{f[0]}:{f[1]}"
        _fault_pending.set(None)
    ev = (verb, cid, kind, t0, t1, nbytes, outcome, qw)
    with r.lock:
        r.buf[r.n % r.size] = ev
        r.n += 1


def record_dispatch(verb: str, kind: str, header: dict, t_arr: float,
                    t_run: float, nbytes: int = 0, outcome: str = "ok"):
    """Shared server/dispatch-side span recorder for the three transports
    (protocol._dispatch, ringconn._handle_slow, gcs._handle): one place
    defines the join key and the queue-wait = handler start − arrival."""
    record(verb, header.get("corr") or header.get("fid"), kind, t_arr,
           time.monotonic(), nbytes, outcome, qw=t_run - t_arr)


def note_fault(point: str, kind: str):
    """Called by the faultpoints plane on every injection: records the hit
    as an instant event and stamps the enclosing span (consumed by the
    next ``record`` in this task/thread context whose window covers the
    hit)."""
    if _rec is None:
        return
    t = time.monotonic()
    record(f"fault.{point}", None, "fault", t, t, 0, kind)
    _fault_pending.set((point, kind, t))


def _collect(r: _Recorder) -> List[tuple]:
    if r.n <= r.size:
        return [e for e in r.buf[: r.n]]
    start = r.n % r.size
    return r.buf[start:] + r.buf[:start]


def snapshot() -> Dict[str, Any]:
    """Non-destructive copy of this process's ring + clock anchors."""
    return _snap(drain=False)


def drain() -> Dict[str, Any]:
    """Snapshot and clear the ring (the ``flight_drain`` verb)."""
    return _snap(drain=True)


def _snap(drain: bool) -> Dict[str, Any]:
    r = _rec
    base = {
        "proc": _label or f"pid{os.getpid()}",
        "pid": os.getpid(),
        "token": _PROC_TOKEN,
        "now": time.time(),
    }
    if r is None:
        return {**base, "anchor_wall": base["now"],
                "anchor_mono": time.monotonic(), "recorded": 0,
                "dropped": 0, "events": []}
    with r.lock:
        events = _collect(r)
        recorded = r.n
        dropped = max(r.n - r.size, 0)
        if drain:
            r.buf = [None] * r.size
            r.n = 0
    return {**base, "anchor_wall": r.anchor_wall,
            "anchor_mono": r.anchor_mono, "recorded": recorded,
            "dropped": dropped, "events": events}


# ------------------------------------------------------------------- merge

def merge_snapshots(snaps: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Normalize per-process snapshots into one clock-aligned event list.

    Each snapshot carries ``anchor_wall``/``anchor_mono`` plus an optional
    ``offset`` (seconds to ADD to its wall times — the head computes it per
    node from the drain RPC's midpoint vs the node's reported wall clock,
    correcting skew between machines). Output is sorted by corrected start
    time; each event dict carries proc/pid/verb/cid/kind/ts/dur/nbytes/
    outcome/qw with ``ts`` in wall seconds on the head's clock."""
    out: List[Dict[str, Any]] = []
    for s in snaps:
        if not s:
            continue
        off = float(s.get("offset") or 0.0)
        aw = float(s.get("anchor_wall") or 0.0)
        am = float(s.get("anchor_mono") or 0.0)
        proc = s.get("proc") or f"pid{s.get('pid')}"
        pid = s.get("pid")
        for ev in s.get("events") or ():
            verb, cid, kind, t0, t1, nbytes, outcome, qw = ev
            out.append({
                "proc": proc, "pid": pid, "verb": verb, "cid": cid,
                "kind": kind, "ts": aw + (t0 - am) + off,
                "dur": max(t1 - t0, 0.0), "nbytes": nbytes,
                "outcome": outcome, "qw": qw,
            })
    out.sort(key=lambda e: e["ts"])
    return out


def to_chrome_trace(merged: List[Dict[str, Any]],
                    t0: Optional[float] = None) -> List[Dict[str, Any]]:
    """Merged events → Chrome trace-event JSON (the ``traceEvents`` array
    form, loadable in Perfetto / chrome://tracing).

    - One complete ("X") event per span: pid = process label, tid = span
      kind, args carry cid/outcome/bytes/queue-wait.
    - Flow ("s"/"f") event pairs stitch spans sharing a correlation id
      across processes, so Perfetto draws the cross-process arrows.
    - ``t0``: subtract this wall time from every timestamp. Default: the
      earliest span (trace starts at 0); pass 0.0 to keep absolute wall
      microseconds (``rt timeline --rpc`` interleaves with task events that
      use absolute timestamps).
    """
    if not merged:
        return []
    if t0 is None:
        t0 = min(e["ts"] for e in merged)
    trace: List[Dict[str, Any]] = []
    by_cid: Dict[str, List[dict]] = {}
    for e in merged:
        ts_us = (e["ts"] - t0) * 1e6
        trace.append({
            "name": e["verb"], "cat": e["kind"], "ph": "X",
            "ts": ts_us, "dur": e["dur"] * 1e6,
            "pid": e["proc"], "tid": e["kind"],
            "args": {
                "cid": e["cid"], "outcome": e["outcome"],
                "bytes": e["nbytes"],
                "queue_wait_ms": round(e["qw"] * 1e3, 3),
            },
        })
        if e["cid"]:
            by_cid.setdefault(str(e["cid"]), []).append(e)
    for cid, evs in by_cid.items():
        if len({e["proc"] for e in evs}) < 2:
            continue
        evs.sort(key=lambda e: e["ts"])
        first = evs[0]
        for k, nxt in enumerate(evs[1:]):
            # One s→f chain per flow id (the trace-event format binds
            # flows by id): a cid recorded by 3+ spans gets one distinct
            # flow per (origin, follower) pair, not a shared id.
            fid = f"{cid}/{k}"
            trace.append({
                "name": "rpc", "cat": "rpc_flow", "ph": "s", "id": fid,
                "ts": (first["ts"] - t0) * 1e6, "pid": first["proc"],
                "tid": first["kind"],
            })
            trace.append({
                "name": "rpc", "cat": "rpc_flow", "ph": "f", "bp": "e",
                "id": fid, "ts": (nxt["ts"] - t0) * 1e6 + 0.001,
                "pid": nxt["proc"], "tid": nxt["kind"],
            })
    return trace


def attribution(merged: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Per-verb time attribution over a merged event list: count, total
    busy seconds, mean/max latency, total queue wait and bytes. This is the
    table ``bench.py --flight`` prints next to the BENCH json."""
    out: Dict[str, Dict[str, float]] = {}
    for e in merged:
        rec = out.setdefault(e["verb"], {
            "count": 0, "total_s": 0.0, "max_ms": 0.0,
            "queue_wait_s": 0.0, "bytes": 0,
        })
        rec["count"] += 1
        rec["total_s"] += e["dur"]
        rec["max_ms"] = max(rec["max_ms"], e["dur"] * 1e3)
        rec["queue_wait_s"] += e["qw"]
        rec["bytes"] += int(e["nbytes"] or 0)
    for rec in out.values():
        rec["mean_ms"] = (
            rec["total_s"] * 1e3 / rec["count"] if rec["count"] else 0.0
        )
    return out


def format_attribution(attrib: Dict[str, Dict[str, float]]) -> str:
    """Fixed-width table of :func:`attribution`, heaviest verbs first."""
    rows = sorted(attrib.items(), key=lambda kv: -kv[1]["total_s"])
    lines = [
        f"{'verb':<28}{'count':>9}{'total_s':>10}{'mean_ms':>9}"
        f"{'max_ms':>9}{'qwait_s':>9}{'MB':>8}"
    ]
    for verb, r in rows:
        lines.append(
            f"{verb:<28}{r['count']:>9}{r['total_s']:>10.3f}"
            f"{r['mean_ms']:>9.3f}{r['max_ms']:>9.1f}"
            f"{r['queue_wait_s']:>9.3f}{r['bytes'] / 1e6:>8.1f}"
        )
    return "\n".join(lines)


def _load_env():
    """Process-start configuration (RT_FLIGHT_ENABLED / flight_enabled via
    rt_config, propagated to spawned workers through the environment)."""
    try:
        from ray_tpu._private.config import rt_config

        if rt_config.flight_enabled:
            enable(int(rt_config.flight_ring_size))
    except Exception as e:
        logger.debug("flight env config unavailable: %s", e)


_load_env()
