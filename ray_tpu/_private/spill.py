"""Object spilling: sealed arena objects overflow to external storage under
memory pressure and are restored on demand.

Reference behavior being reproduced (not copied):
``src/ray/raylet/local_object_manager.h:46`` — SpillObjects (:144) writes
primary copies to external storage and frees the store memory;
AsyncRestoreSpilledObject (:156) reads them back on demand;
``python/ray/_private/external_storage.py`` — pluggable storage backends
(filesystem and cloud URIs) behind one interface. Here the backend registry
maps URI schemes to storage classes: ``file://`` (or a bare path) writes
the frame format below to local disk, ``memory://`` is an in-process store
for tests, and ``gs://``/``s3://`` route through fsspec when installed
(loud ImportError otherwise — a TPU pod wants overflow in GCS buckets, not
host disk). ``register_spill_storage`` lets deployments plug their own.

IO runs on a small thread pool so a spill burst writes objects in parallel
and an event-loop caller never blocks on a disk/bucket read (the worker
routes restores through it).

File format: little-endian u32 frame count, u32 lengths, then the frames
back to back (no alignment: files are read sequentially, not mapped into
typed views).
"""
from __future__ import annotations

import logging
import os
import struct
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private import faultpoints

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")


def _pack_parts(frames: List) -> Tuple[List, int]:
    """Wire parts for one spilled object, WITHOUT copying frame payloads
    (join/write accept buffer views directly): under memory pressure an
    extra full copy per object is exactly what a spill must not make."""
    total = 0
    parts: List = [_U32.pack(len(frames))]
    for fr in frames:
        parts.append(_U32.pack(len(fr)))
    for fr in frames:
        parts.append(fr)
        total += len(fr)
    return parts, total


def _unpack(blob: bytes) -> List[bytes]:
    (count,) = _U32.unpack_from(blob, 0)
    pos = 4
    lens = []
    for _ in range(count):
        lens.append(_U32.unpack_from(blob, pos)[0])
        pos += 4
    out = []
    for n in lens:
        out.append(blob[pos : pos + n])
        pos += n
    return out


class FileSpillStorage:
    """Local-filesystem backend (``file://`` or a bare path). URIs are
    plain paths so other processes on a shared filesystem can read them
    directly."""

    def __init__(self, root: str):
        self.root = root
        self._made = False

    def write(self, key: str, frames: List) -> Tuple[str, int]:
        if not self._made:
            os.makedirs(self.root, exist_ok=True)
            self._made = True
        path = os.path.join(self.root, key)
        total = 0
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_U32.pack(len(frames)))
            for fr in frames:
                f.write(_U32.pack(len(fr)))
            for fr in frames:
                f.write(fr)
                total += len(fr)
        os.replace(tmp, path)  # atomic publish, mirroring the arena rename
        return path, total

    def read(self, uri: str) -> Optional[List[bytes]]:
        try:
            with open(uri, "rb") as f:
                (count,) = _U32.unpack(f.read(4))
                lens = [_U32.unpack(f.read(4))[0] for _ in range(count)]
                return [f.read(n) for n in lens]
        except (OSError, struct.error):
            return None

    def delete(self, uri: str):
        try:
            os.unlink(uri)
        except OSError:
            pass

    def cleanup(self):
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)


class MemorySpillStorage:
    """In-process dict store (``memory://``): the mocked remote backend for
    tests — exercises the full scheme-routing/restore path without a real
    bucket."""

    _stores: Dict[str, Dict[str, bytes]] = {}
    _lock = threading.Lock()

    def __init__(self, root: str):
        self.root = root.rstrip("/")
        with self._lock:
            self._store = self._stores.setdefault(self.root, {})

    def write(self, key: str, frames: List) -> Tuple[str, int]:
        parts, total = _pack_parts(frames)
        uri = f"{self.root}/{key}"
        blob = b"".join(parts)  # the one unavoidable copy: the store IS ram
        with self._lock:
            self._store[uri] = blob
        return uri, total

    def read(self, uri: str) -> Optional[List[bytes]]:
        with self._lock:
            blob = self._store.get(uri)
        return _unpack(blob) if blob is not None else None

    def delete(self, uri: str):
        with self._lock:
            self._store.pop(uri, None)

    def cleanup(self):
        with self._lock:
            self._store.clear()


class FsspecSpillStorage:
    """Cloud-bucket backend over fsspec (``gs://``, ``s3://``, ...).
    Import-gated: the TPU image may not ship gcsfs/s3fs, and a spill
    configured for a bucket must fail LOUDLY, not silently write to disk."""

    def __init__(self, root: str):
        try:
            import fsspec
        except ImportError as e:
            raise ImportError(
                f"spill_dir={root!r} needs the optional 'fsspec' (plus the "
                f"scheme's driver, e.g. gcsfs for gs://); pip install it or "
                f"point spill_dir at a local path"
            ) from e
        self.root = root.rstrip("/")
        self._fs, _ = fsspec.core.url_to_fs(self.root)

    def write(self, key: str, frames: List) -> Tuple[str, int]:
        parts, total = _pack_parts(frames)
        uri = f"{self.root}/{key}"
        with self._fs.open(uri, "wb") as f:
            for p in parts:  # stream: no full-object in-RAM copy
                f.write(p)
        return uri, total

    def read(self, uri: str) -> Optional[List[bytes]]:
        try:
            with self._fs.open(uri, "rb") as f:
                return _unpack(f.read())
        except Exception:
            return None

    def delete(self, uri: str):
        try:
            self._fs.rm(uri)
        except Exception:
            pass

    def cleanup(self):
        try:
            self._fs.rm(self.root, recursive=True)
        except Exception:
            pass


# scheme -> storage factory(root_uri). Deployments/tests may register more
# (reference: external storage config by type).
STORAGE_SCHEMES: Dict[str, Callable[[str], object]] = {
    "file": lambda uri: FileSpillStorage(uri[len("file://"):] or "/"),
    "memory": MemorySpillStorage,
    "gs": FsspecSpillStorage,
    "s3": FsspecSpillStorage,
    "gcs": FsspecSpillStorage,
}


def register_spill_storage(scheme: str, factory: Callable[[str], object]):
    STORAGE_SCHEMES[scheme] = factory


def _storage_for(uri: str):
    scheme = uri.split("://", 1)[0] if "://" in uri else ""
    if not scheme:
        return FileSpillStorage(uri)
    factory = STORAGE_SCHEMES.get(scheme)
    if factory is None:
        raise ValueError(
            f"no spill storage registered for scheme {scheme!r} "
            f"(have: {sorted(STORAGE_SCHEMES)}); "
            f"register_spill_storage() adds one"
        )
    return factory(uri)


class SpillManager:
    """Spill/restore against the configured storage backend, with a small
    IO pool (writes in a pressure burst run in parallel; loop callers
    restore without blocking) and running counters surfaced to the
    metrics plane."""

    _IO_THREADS = 4

    def __init__(self, root: Optional[str] = None, session: str = ""):
        from ray_tpu._private.config import rt_config

        env_root = rt_config.spill_dir or None
        target = root or env_root or os.path.join(
            tempfile.gettempdir(), f"rt_spill_{session or os.getpid()}"
        )
        self.storage = _storage_for(target)
        # Plain-path root kept for the file backend (back-compat paths);
        # scheme backends expose their base uri here.
        self.root = getattr(self.storage, "root", target)
        # A user-supplied target (env or arg) may be shared by other
        # sessions (e.g. NFS, a bucket): never wipe it wholesale.
        self._owns_root = root is None and env_root is None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()
        # Guarded: IO-pool threads update these concurrently, and a lost
        # read-modify-write would permanently under-report the gauges.
        self._stats_lock = threading.Lock()
        self.stats = {
            "spilled_objects": 0, "spilled_bytes": 0,
            "restored_objects": 0, "restored_bytes": 0,
        }

    def stats_snapshot(self) -> dict:
        """Consistent copy of the spill/restore counters for the metrics
        plane (IO-pool threads mutate them concurrently; a torn read
        could pair a new spilled_objects with an old spilled_bytes)."""
        with self._stats_lock:
            return dict(self.stats)

    @property
    def pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            with self._pool_lock:
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(
                        max_workers=self._IO_THREADS,
                        thread_name_prefix="rt-spill",
                    )
        return self._pool

    def key_uri(self, object_hex: str) -> str:
        """The uri ``spill(object_hex, ...)`` would produce — for callers
        that must delete a possibly-spilled object without holding its
        meta."""
        if isinstance(self.storage, FileSpillStorage):
            return os.path.join(self.root, object_hex)
        return f"{self.root}/{object_hex}"

    def spill(self, object_hex: str, frames: List) -> dict:
        """Write frames to the backend; returns the meta for the copy."""
        if faultpoints.ACTIVE:
            # error = storage write failure: spill_many logs it and keeps
            # the object in the arena (exactly a full/unreachable bucket).
            faultpoints.fire("spill.write", err=OSError)
        uri, total = self.storage.write(object_hex, frames)
        with self._stats_lock:
            self.stats["spilled_objects"] += 1
            self.stats["spilled_bytes"] += total
        return {"spill": uri, "size": total}

    def spill_many(self, items: List[Tuple[str, List]]) -> List[Optional[dict]]:
        """Spill a batch in parallel on the IO pool (reference: SpillObjects
        takes a batch; IO workers run the writes). Entry i is None when
        that write failed."""
        if not items:
            return []
        futs = [
            self.pool.submit(self.spill, hex_, frames)
            for hex_, frames in items
        ]
        out: List[Optional[dict]] = []
        for hex_, fut in zip((h for h, _ in items), futs):
            try:
                out.append(fut.result())
            except Exception:
                logger.exception("spill of %s failed", hex_[:12])
                out.append(None)
        return out

    def read(self, meta: dict) -> Optional[List[bytes]]:
        uri = meta.get("spill")
        if not uri:
            return None
        if faultpoints.ACTIVE:
            try:
                faultpoints.fire("spill.restore", err=OSError)
            except OSError as e:
                # Missing/unreadable external copy: same contract as a
                # backend read failure — None routes callers to the
                # fallback pull/reconstruction paths.
                logger.debug("injected restore failure for %s: %s", uri, e)
                return None
        frames = _storage_for_uri(self.storage, uri).read(uri)
        if frames is not None:
            with self._stats_lock:
                self.stats["restored_objects"] += 1
                self.stats["restored_bytes"] += sum(len(f) for f in frames)
        return frames

    async def read_async(self, meta: dict, loop) -> Optional[List[bytes]]:
        """Restore without blocking the caller's event loop (reference:
        AsyncRestoreSpilledObject — restore is IO-worker work)."""
        return await loop.run_in_executor(self.pool, self.read, meta)

    def delete(self, meta: dict):
        uri = meta.get("spill")
        if uri:
            _storage_for_uri(self.storage, uri).delete(uri)

    def cleanup(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if not self._owns_root:
            return  # shared target: other sessions' spills live here
        try:
            self.storage.cleanup()
        except Exception:
            pass


def _storage_for_uri(default_storage, uri: str):
    """Route a READ/DELETE by the uri's own scheme: metas can arrive from
    peers configured with a different backend (e.g. this node spills to
    file://, a peer spilled to gs://)."""
    scheme = uri.split("://", 1)[0] if "://" in uri else ""
    default_scheme = ""
    root = getattr(default_storage, "root", "")
    if "://" in str(root):
        default_scheme = str(root).split("://", 1)[0]
    if scheme == default_scheme and (
        not scheme or uri.startswith(str(root))
    ):
        return default_storage
    if not scheme:
        return default_storage if isinstance(
            default_storage, FileSpillStorage
        ) else FileSpillStorage(os.path.dirname(uri) or "/")
    if scheme == "memory":
        # must hit the SAME in-process store the writer used
        return MemorySpillStorage(uri.rsplit("/", 1)[0])
    return _storage_for(uri.rsplit("/", 1)[0])
