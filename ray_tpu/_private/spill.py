"""Object spilling: sealed arena objects overflow to disk under memory
pressure and are restored on demand.

Reference behavior being reproduced (not copied):
``src/ray/raylet/local_object_manager.h:46`` — SpillObjects (:144) writes
primary copies to external storage and frees the store memory;
AsyncRestoreSpilledObject (:156) reads them back on demand. The reference
runs spill IO in dedicated workers against pluggable storage
(``python/ray/_private/external_storage.py``); here spilling is a library
call made by the process that hits arena pressure — the arena's
pin/seal/delete protocol (native/src/arena_store.cc) already makes
concurrent spill vs. read crash-safe, so no broker process is needed.

File format: little-endian u32 frame count, u32 lengths, then the frames
back to back (no alignment: files are read sequentially, not mapped into
typed views).
"""
from __future__ import annotations

import logging
import os
import struct
import tempfile
import threading
from typing import List, Optional

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")


class SpillManager:
    """Writes/reads spilled objects under one session-scoped directory.

    Paths embed a random token so a crashed session's leftovers can never be
    read by the next one (the directory is also session-named).
    """

    def __init__(self, root: Optional[str] = None, session: str = ""):
        from ray_tpu._private.config import rt_config

        env_root = rt_config.spill_dir or None
        self.root = root or env_root or os.path.join(
            tempfile.gettempdir(), f"rt_spill_{session or os.getpid()}"
        )
        # A user-supplied directory (env or arg) may be shared by other
        # sessions (e.g. NFS): never rmtree it wholesale at teardown.
        self._owns_root = root is None and env_root is None
        self._lock = threading.Lock()
        self._made = False

    def _ensure_dir(self):
        if not self._made:
            os.makedirs(self.root, exist_ok=True)
            self._made = True

    def spill(self, object_hex: str, frames: List) -> dict:
        """Write frames to disk; returns the meta describing the copy."""
        self._ensure_dir()
        path = os.path.join(self.root, object_hex)
        total = 0
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_U32.pack(len(frames)))
            for fr in frames:
                f.write(_U32.pack(len(fr)))
            for fr in frames:
                f.write(fr)
                total += len(fr)
        os.replace(tmp, path)  # atomic publish, mirroring the arena rename
        return {"spill": path, "size": total}

    def read(self, meta: dict) -> Optional[List[bytes]]:
        path = meta.get("spill")
        if not path:
            return None
        try:
            with open(path, "rb") as f:
                (count,) = _U32.unpack(f.read(4))
                lens = [_U32.unpack(f.read(4))[0] for _ in range(count)]
                return [f.read(n) for n in lens]
        except (OSError, struct.error):
            return None

    def delete(self, meta: dict):
        path = meta.get("spill")
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    def cleanup(self):
        if not self._owns_root:
            return  # shared directory: other sessions' spills live here
        try:
            import shutil

            shutil.rmtree(self.root, ignore_errors=True)
        except Exception:
            pass
