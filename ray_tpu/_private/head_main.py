"""Standalone head process entrypoint (``raytpu start --head``).

Reference analog: ``gcs_server_main.cc`` + the head-node pieces of
``ray start --head`` (``scripts/scripts.py:799``): the head service, an
optional local worker node, and the dashboard, in one process tree. The
head address is published to a well-known file so drivers can
``init(address="auto")`` (reference: the bootstrap address file in the
session dir).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import tempfile


def address_file_path() -> str:
    d = os.path.join(tempfile.gettempdir(), "ray_tpu")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "head_address")


def read_address_file():
    try:
        with open(address_file_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-cpus", type=int, default=0,
                        help="CPUs for the colocated worker node (0 = none)")
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--dashboard-port", type=int, default=-1,
                        help="-1 disables the dashboard; 0 picks a port")
    parser.add_argument("--no-address-file", action="store_true",
                        help="skip the global head_address file (cluster "
                             "launchers manage per-cluster info files; two "
                             "clusters must not fight over one global file)")
    parser.add_argument("--info-file", default=None,
                        help="also write the startup info JSON here "
                             "(atomic; for cluster launchers)")
    parser.add_argument("--state-file", default=None,
                        help="persist durable head state (KV, jobs) here; "
                             "restored on restart (GCS fault tolerance)")
    parser.add_argument("--state-save-interval", type=float, default=5.0)
    parser.add_argument("--log-level", default="WARNING")
    args = parser.parse_args(argv)

    import logging

    logging.basicConfig(level=getattr(logging, args.log_level.upper(), 30))

    from ray_tpu._private import auth as _auth
    from ray_tpu._private.config import rt_config
    from ray_tpu._private.gcs import HeadService
    from ray_tpu._private.ids import JobID
    from ray_tpu._private.node import spawn_node

    # Cluster auth token, minted at head start (reference:
    # src/ray/rpc/authentication/): every node/driver/xfer connection must
    # present it first. Rides the env to spawned nodes and the (0600)
    # address/info files to drivers; RT_AUTH_TOKEN= (explicitly empty) is
    # the opt-out.
    _auth.ensure_cluster_token()

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    head = HeadService()
    port = args.port
    if args.state_file:
        head.load_from_file(args.state_file)
        # Replay WAL over the snapshot: durable mutations since the last
        # snapshot write (reference: Redis-store per-mutation durability).
        try:
            n = head.replay_wal(args.state_file + ".wal")
            if n:
                logging.getLogger(__name__).info(
                    "replayed %d WAL records", n
                )
        except Exception:
            logging.getLogger(__name__).exception("WAL replay failed")
        # Rebind the previous port (unless one was given explicitly) so
        # live nodes/drivers holding the old address can rejoin — the
        # worker side retries its head connection on loss (live-cluster
        # rejoin; reference: GCS restarts behind a stable address).
        restored = getattr(head, "restored_addr", None)
        if port == 0 and restored:
            port = restored[1]
    try:
        addr = loop.run_until_complete(head.start(args.host, port))
    except OSError:
        if port == args.port:
            raise
        # Restored port taken (e.g. another service grabbed it while the
        # head was down): fall back to an ephemeral port rather than die.
        addr = loop.run_until_complete(head.start(args.host, args.port))

    if args.state_file:
        wal = head.attach_wal(args.state_file + ".wal")
        # In-flight off-loop snapshot write, visible to the shutdown path:
        # a stale write completing AFTER the final save would clobber it
        # (and the WAL is deleted by then — silent data loss).
        inflight = {"fut": None}

        def _write_state(blob):
            # one executor hop: old-generation fsync + snapshot write
            wal.sync_retired()
            head.write_snapshot(args.state_file, blob)

        async def _persist_loop():
            while True:
                await asyncio.sleep(args.state_save_interval)
                try:
                    # Rotate the WAL, then snapshot, both ON the loop (no
                    # op can fall between); write+fsync OFF it. Old WAL
                    # generations die only after the snapshot is durable.
                    old_gen = wal.rotate()
                    blob = head.snapshot()
                    inflight["fut"] = loop.run_in_executor(
                        None, _write_state, blob
                    )
                    await inflight["fut"]
                    inflight["fut"] = None
                    wal.delete_through(old_gen)
                except Exception:
                    logging.getLogger(__name__).exception(
                        "head state persistence failed; will retry"
                    )

        persist_task = loop.create_task(_persist_loop())

    dash_port = None
    dashboard = None
    if args.dashboard_port >= 0:
        from ray_tpu.dashboard import DashboardApp

        dashboard = DashboardApp(head, args.host, args.dashboard_port)
        dash_port = loop.run_until_complete(dashboard.start())

    node = None
    if args.num_cpus > 0:
        resources = {"CPU": float(args.num_cpus)}
        resources.update(json.loads(args.resources))
        node = spawn_node(addr, JobID.from_random(), resources, {}, None)

    info = {
        "address": f"{addr[0]}:{addr[1]}",
        "dashboard_port": dash_port,
        "head_pid": os.getpid(),
        "node_pids": [node.proc.pid] if node else [],
        "auth_token": rt_config.auth_token,
    }
    def _write_private(path: str, payload: dict):
        """0600 from CREATION (open-then-chmod leaves a window where
        another local user reads the token off the well-known path)."""
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        os.chmod(path, 0o600)  # a pre-existing 0644 file keeps its mode
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)

    if not args.no_address_file:
        _write_private(address_file_path(), info)
    if args.info_file:
        # atomic publish for launchers polling a private path (a cluster
        # launcher must not read another cluster's global address file)
        tmp = args.info_file + ".tmp"
        _write_private(tmp, info)
        os.replace(tmp, args.info_file)
    # Parseable by the CLI parent. REDACTED: stdout routinely lands in
    # 0644 log files (launchers redirect it); the token's distribution
    # channel is the 0600 files, never a log line.
    print(json.dumps(_auth.redacted(info)), flush=True)

    def term(*_):
        loop.stop()

    # loop.add_signal_handler, NOT signal.signal: a raw handler that calls
    # loop.stop() cannot wake a selector blocked on a long timeout — PEP
    # 475 retries the poll after the handler returns, so shutdown would
    # wait out the persist timer (observed: hung head with a 1h interval).
    # asyncio's handler rides the loop's self-pipe and wakes it instantly.
    try:
        loop.add_signal_handler(signal.SIGTERM, term)
        loop.add_signal_handler(signal.SIGINT, term)
    except (NotImplementedError, RuntimeError):  # non-main thread/platform
        signal.signal(signal.SIGTERM, term)
        signal.signal(signal.SIGINT, term)
    exit_code = 0
    try:
        loop.run_forever()
    except BaseException:  # crash must not report success to supervisors
        exit_code = 1
        raise
    finally:
        if args.state_file:
            try:
                # the persist task must not tick against a closed WAL while
                # the loop drains below
                persist_task.cancel()
                # join any in-flight executor snapshot write first: its
                # os.replace landing after the final save would roll the
                # state file back to a pre-shutdown blob
                fut = inflight.get("fut")
                if fut is not None and not fut.done():
                    try:
                        loop.run_until_complete(
                            asyncio.wait_for(asyncio.shield(fut), timeout=10)
                        )
                    except Exception:
                        pass
                head.save_to_file(args.state_file)
                from ray_tpu._private.wal import delete_all

                head.wal.close()
                # clean shutdown: the snapshot covers everything
                delete_all(args.state_file + ".wal")
            except OSError:
                pass
        if node is not None:
            node.terminate()
        for coro in ([dashboard.stop()] if dashboard else []) + [head.close()]:
            try:
                loop.run_until_complete(asyncio.wait_for(coro, timeout=3))
            except Exception:
                pass
        if not args.no_address_file:
            try:
                os.remove(address_file_path())
            except OSError:
                pass
        os._exit(exit_code)  # no lingering non-daemon threads may block exit


if __name__ == "__main__":
    main()
