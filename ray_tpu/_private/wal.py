"""Write-ahead log for durable head state.

Reference analog: GCS fault tolerance via a Redis-backed store
(``src/ray/gcs/store_client/redis_store_client.cc``) — every durable table
mutation is persisted as it happens, not on a snapshot timer. The TPU-era
head keeps the snapshot-and-replay shape (``gcs.py snapshot/restore``) and
closes the between-snapshots loss window with this log: durable mutations
(KV puts/deletes, job records) append a record before the RPC reply, and
restart replays snapshot + WAL.

Format: per record ``<u32 len><u32 crc32><payload>`` where payload is a
pickled op dict. Replay stops at the first short/corrupt record (a torn
tail write is expected on crash — everything before it is intact).

Generational rotation ties the log to the snapshot cycle: rotate() opens
generation N+1 *before* the snapshot captures state (both on the head's
event loop, so no op falls between), and once the snapshot is durably on
disk the old generations are deleted. Restore replays every surviving
generation in order — replay is idempotent (puts overwrite, deletes are
best-effort), so a failed snapshot write only means replaying more.

fsync policy: appends are buffered+flushed synchronously (survives process
crash); fsync (survives host crash) is coalesced off the event loop — the
same durability-vs-latency point as Redis ``appendfsync everysec``.
"""
from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional

_HDR = struct.Struct("<II")


class WalWriter:
    def __init__(self, path_prefix: str):
        self.prefix = path_prefix
        d = os.path.dirname(os.path.abspath(path_prefix))
        os.makedirs(d, exist_ok=True)
        gens = existing_generations(path_prefix)
        self.gen = (gens[-1] + 1) if gens else 0
        self._f = open(self._path(self.gen), "ab")
        self._fsync_pending = False
        self._dirty = False          # bytes appended since last fsync start
        self._retired: List[Any] = []  # rotated-out files awaiting fsync

    def _path(self, gen: int) -> str:
        return f"{self.prefix}.{gen:08d}"

    def append(self, op: Dict[str, Any]) -> None:
        if self._f.closed:
            raise ValueError("WAL closed")
        payload = pickle.dumps(op, protocol=pickle.HIGHEST_PROTOCOL)
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()  # survives process crash; host-crash via fsync
        self._dirty = True

    def schedule_fsync(self, loop) -> None:
        """Coalesced off-loop fsync: at most one in flight, and appends
        that land DURING an in-flight fsync re-arm a follow-up (the
        trailing bytes must not wait for the next snapshot tick)."""
        if self._fsync_pending or not self._dirty:
            return
        self._fsync_pending = True
        self._dirty = False  # covers bytes appended up to this point
        f = self._f

        def _sync():
            try:
                os.fsync(f.fileno())
            except (OSError, ValueError):  # rotated/closed underneath
                pass

        def _done(_):
            self._fsync_pending = False
            if self._dirty and not self._f.closed:
                self.schedule_fsync(loop)  # appends arrived mid-flight

        try:
            fut = loop.run_in_executor(None, _sync)
            fut.add_done_callback(_done)
        except RuntimeError:  # loop closing
            self._fsync_pending = False

    def rotate(self) -> int:
        """Switch appends to a fresh generation; returns the OLD gen id.
        Call on the event loop immediately before snapshotting. The old
        file is flushed here (cheap) but fsync'd+closed lazily off-loop —
        call sync_retired() from the same executor hop that writes the
        snapshot (an on-loop fsync would stall every RPC for its
        duration)."""
        old = self.gen
        old_f = self._f
        if old_f.closed:
            raise ValueError("WAL closed")
        self.gen += 1
        self._f = open(self._path(self.gen), "ab")
        try:
            old_f.flush()
        except OSError:
            pass
        self._retired.append(old_f)
        return old

    def sync_retired(self) -> None:
        """fsync + close rotated-out generations (call OFF the loop)."""
        retired, self._retired = self._retired, []
        for f in retired:
            try:
                os.fsync(f.fileno())
            except (OSError, ValueError):
                pass
            try:
                f.close()
            except OSError:
                pass

    def delete_through(self, gen: int) -> None:
        """Remove generations <= gen (their ops are in a durable snapshot)."""
        for g in existing_generations(self.prefix):
            if g <= gen and g != self.gen:
                try:
                    os.remove(self._path(g))
                except OSError:
                    pass

    def close(self) -> None:
        self.sync_retired()
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError:
            pass
        self._f.close()


def existing_generations(path_prefix: str) -> List[int]:
    d = os.path.dirname(os.path.abspath(path_prefix)) or "."
    base = os.path.basename(path_prefix)
    gens = []
    try:
        for name in os.listdir(d):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    gens.append(int(suffix))
    except OSError:
        pass
    return sorted(gens)


def replay_file(path: str) -> Iterator[Dict[str, Any]]:
    """Yield ops until EOF or the first torn/corrupt record."""
    try:
        f = open(path, "rb")
    except OSError:
        return
    with f:
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return
            length, crc = _HDR.unpack(hdr)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return  # torn tail write: everything before it was intact
            try:
                yield pickle.loads(payload)
            except Exception:
                return


def replay_all(path_prefix: str) -> Iterator[Dict[str, Any]]:
    for gen in existing_generations(path_prefix):
        yield from replay_file(f"{path_prefix}.{gen:08d}")


def delete_all(path_prefix: str) -> None:
    for gen in existing_generations(path_prefix):
        try:
            os.remove(f"{path_prefix}.{gen:08d}")
        except OSError:
            pass
