"""Pre-framed task-spec templates + function push-through ledger.

Submission-plane analog of the reference's cached ``TaskSpec`` protos
(``common/task/task_spec.h`` — the immutable spec is built once per
function/options pair and reused across submissions): the invariant
portion of a ``push_task`` header (owner address, task name, runtime env,
retry budget) is serialized to ONE msgpack blob per (function, options)
on the submitting worker and spliced into every wire message as an opaque
frame. The pump-thread hot path then packs a 4-key per-call delta header
(task id, function key, return count, spec flag) instead of re-framing
the full spec for every task in a burst; the executing side decodes each
distinct spec blob once through :class:`SpecCache`.

:class:`FnPushLedger` is the second half of the submission cache: the
exporter keeps the cloudpickle blob of every function it has exported
(or loaded) and piggybacks it on the FIRST ``push_task`` carrying that
fkey to each peer (wire flag ``fb``), so a fresh worker installs the
function from the push itself instead of issuing a ``gcs.kv_get`` — the
function table becomes a fallback, not a hot path (reference: function
table pushes ride the same channel as task specs in
``core_worker/transport``).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Set, Tuple

import msgpack

# Keys a spec template may carry; everything else in a push_task header is
# a per-call delta (tid, fkey, nret, argrefs, borrows, trace, corr ids).
SPEC_KEYS = ("owner", "name", "renv", "retries")


def pack_spec(spec: dict) -> bytes:
    """Serialize the invariant spec fields once (template build time)."""
    return msgpack.packb(spec, use_bin_type=True)


class SpecCache:
    """Receiver-side spec decode cache: spec bytes -> header-fragment dict.

    A burst of K tasks of one function ships K identical spec frames but
    costs ONE unpack here (bytes hash once, then dict hits). Bounded: at
    capacity the oldest half is dropped (specs are tiny and re-decodable,
    so eviction only costs a future unpack). The returned dict is shared —
    callers must merge-copy (``{**spec, **h}``), never mutate.
    """

    def __init__(self, cap: int = 1024):
        self._cap = max(int(cap), 2)
        self._decoded: Dict[bytes, dict] = {}

    def get(self, blob: bytes) -> dict:
        d = self._decoded.get(blob)
        if d is None:
            d = msgpack.unpackb(blob, raw=False)
            if len(self._decoded) >= self._cap:
                # pop, not del: the ring fast path (pump thread) and the
                # loop slow path may evict concurrently
                for k in list(self._decoded)[: self._cap // 2]:
                    self._decoded.pop(k, None)
            self._decoded[blob] = d
        return d


class FnPushLedger:
    """Function-blob push-through bookkeeping on the SUBMITTING side.

    ``store`` keeps the pickled function bytes at export/load time;
    ``blob_for`` returns the blob exactly once per (peer, fkey) — the
    caller attaches it to that push and the peer installs it into its
    function cache. A peer that never receives the blob (batch fallback,
    connection churn) still resolves through the head KV, so this ledger
    only ever removes RPCs, never correctness.

    Thread-safe: the slot pushers run on the core loop but export/load
    can happen from caller threads.
    """

    def __init__(self, cap: int = 256):
        self._cap = max(int(cap), 2)
        self._blobs: Dict[str, bytes] = {}
        self._sent: Dict[Tuple, Set[str]] = {}
        self._lock = threading.Lock()

    def store(self, fkey: str, blob: bytes):
        with self._lock:
            if fkey in self._blobs:
                return
            if len(self._blobs) >= self._cap:
                for k in list(self._blobs)[: self._cap // 2]:
                    del self._blobs[k]
            self._blobs[fkey] = blob

    def blob_for(self, peer, fkey: str) -> Optional[bytes]:
        """The blob to piggyback on this push, or None (already sent to
        this peer, or blob unknown). Marks the peer as covered only when
        a blob is actually returned."""
        with self._lock:
            sent = self._sent.get(peer)
            if sent is not None and fkey in sent:
                return None
            blob = self._blobs.get(fkey)
            if blob is None:
                return None
            if sent is None:
                sent = self._sent[peer] = set()
            sent.add(fkey)
            return blob

    def forget_peer(self, peer):
        """Peer connection torn down: a successor process at the same
        address must be re-covered (it lost its function cache)."""
        with self._lock:
            self._sent.pop(peer, None)
